"""In-graph MetricPack tests: execution-schedule invariance, fault-mask
correctness, the disabled-is-compiled-out contract, and the host record.

The acceptance bar (ISSUE 7): per-round metric records are present and
identical in content — up to documented float re-association — across
``run_round``, ``block_size=N`` and ``streaming=True`` executions of the
same seeded run, with the compile count unchanged when metrics are
disabled (pinned via the telemetry compile counters).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.aggregators import get_aggregator
from blades_tpu.attackers import get_attack
from blades_tpu.core import RoundEngine
from blades_tpu.datasets.fl import FLDataset
from blades_tpu.faults import FaultModel
from blades_tpu.models.common import build_fns
from blades_tpu.models.mlp import MLP
from blades_tpu.telemetry import Recorder, get_recorder, install_jax_monitoring, set_recorder
from blades_tpu.telemetry.metric_pack import (
    NBINS,
    MetricPack,
    pack_dense,
    pack_to_fields,
)

K, SAMPLES, STEPS, BATCH, DIMX = 6, 24, 1, 4, 8


@pytest.fixture(autouse=True)
def _restore_recorder():
    prev = get_recorder()
    yield
    set_recorder(prev)


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(0)
    train_x = rng.randn(K, SAMPLES, DIMX).astype(np.float32)
    train_y = rng.randint(0, 2, (K, SAMPLES)).astype(np.int32)
    counts = np.full(K, SAMPLES, np.int32)
    ds = FLDataset(train_x, train_y, counts, train_x[0], train_y[0])
    spec = build_fns(MLP(hidden=(8,), num_classes=2), sample_shape=(DIMX,))
    params = spec.init(jax.random.PRNGKey(0))
    return ds, spec, params


def _engine(setup, streaming=False, chunks=3, metrics=True, agg="mean",
            fault_model=None, attack="signflipping"):
    ds, spec, params = setup
    return RoundEngine(
        spec.train_loss_fn, spec.eval_logits_fn, params,
        num_clients=K, num_byzantine=2,
        attack=get_attack(attack) if attack else None,
        aggregator=get_aggregator(agg), num_classes=2,
        client_chunks=chunks, streaming=streaming, round_metrics=metrics,
        keep_updates=False, fault_model=fault_model,
    )


def _one_round(eng, setup, agg_key=7):
    ds, spec, params = setup
    key = jax.random.PRNGKey(agg_key)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 0), STEPS, BATCH)
    st = eng.init(params)
    st, m = eng.run_round(st, cx, cy, 0.2, 1.0, key)
    return eng.last_metric_pack


def _assert_packs_match(a: MetricPack, b: MetricPack, exact_fields=True):
    # elementwise fields (norms, histogram, extremes, counts) are
    # bit-exact across schedules; the cosine accumulators fold per chunk
    # and are only re-association-equal (documented in metric_pack.py)
    bitwise = (
        "norm_q", "norm_hist", "n_participants", "n_masked_out",
        "slab_absmax", "slab_norm_max",
    )
    for f in bitwise:
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        if exact_fields:
            np.testing.assert_array_equal(x, y, err_msg=f)
        else:
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-7, err_msg=f)
    for f in ("cos_honest", "cos_byz"):
        np.testing.assert_allclose(
            float(getattr(a, f)), float(getattr(b, f)),
            rtol=1e-5, atol=1e-6, err_msg=f,
        )


def test_dense_block_streaming_identical_content(setup):
    """The acceptance invariant: same seeded round, three execution
    schedules, one metric content (row-local keyless attack so row
    content itself matches the streaming chunk scan)."""
    ds, spec, params = setup
    mp_dense = _one_round(_engine(setup, streaming=False), setup)
    mp_stream = _one_round(_engine(setup, streaming=True), setup)
    _assert_packs_match(mp_dense, mp_stream)

    blk = _engine(setup, streaming=False)
    st = blk.init(params)
    key = jax.random.PRNGKey(7)
    keys = jnp.stack([jax.random.fold_in(key, 0)])
    st, ms, diags = blk.run_block(
        st, keys, [0.2], [1.0], key,
        sampler=ds.traceable_sampler(STEPS, BATCH),
    )
    # block packs are [R]-stacked in diags AND last_metric_pack == last round
    _assert_packs_match(mp_dense, blk.last_metric_pack)
    stacked = diags["metrics"]
    assert np.asarray(stacked.norm_q).shape == (1, 5)
    first = jax.tree_util.tree_map(lambda a: a[0], stacked)
    _assert_packs_match(mp_dense, first)


def test_pack_content_is_meaningful(setup):
    """signflipping: byzantine rows are sign-flipped honest-style rows, so
    the byz mean must point AWAY from where the honest mean points
    relative to the applied aggregate; histogram counts all K rows."""
    mp = _one_round(_engine(setup), setup)
    assert int(mp.n_participants) == K and int(mp.n_masked_out) == 0
    assert int(np.asarray(mp.norm_hist).sum()) == K
    q = np.asarray(mp.norm_q)
    assert (np.diff(q) >= 0).all()  # quantiles are sorted
    assert float(mp.cos_honest) > float(mp.cos_byz)
    assert np.asarray(mp.slab_absmax).shape == (3,)  # client_chunks


def test_fault_mask_excludes_rows_from_metrics(setup):
    """Dropped clients leave the pack: participants+masked_out == K, the
    histogram counts only participants — identically under streaming
    (mask draws are bit-identical to dense, tested in test_streaming)."""
    fm = FaultModel(dropout_rate=0.5)
    mp_d = _one_round(_engine(setup, fault_model=fm), setup)
    mp_s = _one_round(_engine(setup, streaming=True, fault_model=fm), setup)
    n, out = int(mp_d.n_participants), int(mp_d.n_masked_out)
    assert n + out == K and out > 0  # seeded: some row actually dropped
    assert int(np.asarray(mp_d.norm_hist).sum()) == n
    _assert_packs_match(mp_d, mp_s)


def test_disabled_metrics_add_zero_compiles_and_no_pack(setup):
    """Pinned via the compile-counter telemetry: a metrics-off engine and
    a metrics-on engine each compile exactly ONE round program (the pack
    is in-graph — no extra launches), and re-running the metrics-off
    round adds ZERO compiles (the static branch is really compiled out,
    not cached-by-luck)."""
    ds, spec, params = setup
    assert install_jax_monitoring()
    rec = Recorder(enabled=True)
    set_recorder(rec)
    key = jax.random.PRNGKey(3)
    cx, cy = ds.sample_round(jax.random.fold_in(key, 1), STEPS, BATCH)

    def compiles():
        return rec.counters.get("xla.compiles", 0)

    off = _engine(setup, metrics=False)
    st = off.init(params)
    before = compiles()
    st, _ = off.run_round(st, cx, cy, 0.2, 1.0, key)
    off_compiles = compiles() - before
    st, _ = off.run_round(st, cx, cy, 0.2, 1.0, key)
    assert compiles() - before == off_compiles  # re-run: zero new compiles
    assert off.last_metric_pack is None

    on = _engine(setup, metrics=True)
    st2 = on.init(params)
    before = compiles()
    st2, _ = on.run_round(st2, cx, cy, 0.2, 1.0, key)
    on_compiles = compiles() - before
    # metrics ride the SAME program: no extra executable on either side
    assert on_compiles == off_compiles
    assert isinstance(on.last_metric_pack, MetricPack)


def test_pack_dense_function_masked_rows_inert():
    """Unit level: a masked-out row's payload (garbage included) cannot
    change any pack field — same inertness rule as aggregate_masked."""
    rng = np.random.RandomState(1)
    u = rng.randn(5, 16).astype(np.float32)
    mask = np.array([True, True, False, True, True])
    byz = np.array([True, False, False, False, False])
    agg = u[mask].mean(axis=0)
    a = pack_dense(jnp.asarray(u), jnp.asarray(mask), jnp.asarray(byz),
                   jnp.asarray(agg), 2, 3)
    poisoned = u.copy()
    poisoned[2] = 1e30
    b = pack_dense(jnp.asarray(poisoned), jnp.asarray(mask),
                   jnp.asarray(byz), jnp.asarray(agg), 2, 3)
    for f in MetricPack._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )
    assert int(a.n_participants) == 4 and int(a.n_masked_out) == 1


def test_pack_to_fields_matches_schema(setup):
    """The host-side record passes the committed telemetry schema (the
    lint that keeps docs/telemetry_schema.json honest)."""
    from blades_tpu.telemetry.schema import load_schema, validate_record

    mp = _one_round(_engine(setup), setup)
    fields = pack_to_fields(mp)
    assert len(fields["norm_hist"]) == NBINS
    rec = {"t": "metrics", "round": 1, **fields}
    assert validate_record(rec, load_schema()) == []
