"""The committed robustness matrix is a regression gate: every cell of
``results/matrix/matrix.json`` must satisfy the expectation table in
``examples/robustness_matrix.py`` (defense X holds / attack Y wins), and the
committed ``summary.json`` must be in sync with both. A rerun of the matrix
that silently changes a defense's behavior fails here mechanically
(VERDICT r3 weak #6)."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MATRIX = os.path.join(REPO, "results", "matrix", "matrix.json")
SUMMARY = os.path.join(REPO, "results", "matrix", "summary.json")


@pytest.fixture(scope="module")
def matrix():
    if not os.path.exists(MATRIX):
        pytest.skip("no committed matrix artifact")
    with open(MATRIX) as f:
        return json.load(f)


def test_matrix_complete(matrix):
    from examples.robustness_matrix import AGGS, ATTACKS

    for a in ATTACKS:
        for g in AGGS:
            assert g in matrix.get(a, {}), f"missing cell {a} x {g}"


def test_every_expectation_holds(matrix):
    from examples.robustness_matrix import evaluate_expectations

    rows, ok = evaluate_expectations(matrix)
    bad = [r for r in rows if not r["ok"]]
    assert ok, "expectation failures:\n" + "\n".join(
        f"  {r['attack']} x {r['agg']}: top1={r['top1']} rule={r['rule']}"
        for r in bad
    )


def test_summary_in_sync(matrix):
    from examples.robustness_matrix import evaluate_expectations

    assert os.path.exists(SUMMARY), (
        "results/matrix/summary.json missing — regenerate via "
        "examples/robustness_matrix.py"
    )
    with open(SUMMARY) as f:
        summary = json.load(f)
    rows, ok = evaluate_expectations(matrix)
    assert summary["all_ok"] == ok
    assert summary["rounds"] == matrix["_rounds"]
    assert summary["seed"] == matrix["_seed"]
    recorded = {(r["attack"], r["agg"]): r for r in summary["cells"]}
    for r in rows:
        rec = recorded[(r["attack"], r["agg"])]
        assert rec["top1"] == pytest.approx(r["top1"])
        assert rec["ok"] == r["ok"]
        # the committed rule must be the CURRENT expectation — catches a
        # re-tuned EXPECTATIONS table whose summary was not regenerated
        assert rec["rule"] == r["rule"]


def test_gate_detects_neutered_alie(matrix):
    """Mutation test (VERDICT r4 #5): stub ALIE out (attacked cells copied
    from the unattacked row) — the relative band_rel cells must catch it.
    The pre-r5 absolute floors passed this mutation silently."""
    from examples.robustness_matrix import evaluate_expectations

    mutated = json.loads(json.dumps(matrix))
    mutated["alie"] = dict(mutated["none"])
    rows, ok = evaluate_expectations(mutated)
    assert not ok
    bad = {(r["attack"], r["agg"]) for r in rows if not r["ok"]}
    assert ("alie", "median") in bad
    assert ("alie", "trimmedmean") in bad


def test_attack_success_artifact_in_sync(matrix):
    """results/matrix/attack_success.json (BASELINE's 'attack success'
    metric: top-1 degradation vs the same defense unattacked) must be
    derivable from the committed matrix."""
    from examples.robustness_matrix import AGGS, ATTACKS

    path = os.path.join(REPO, "results", "matrix", "attack_success.json")
    assert os.path.exists(path), "regenerate via examples/robustness_matrix.py"
    with open(path) as f:
        success = json.load(f)
    assert success["rounds"] == matrix["_rounds"]
    for a in ATTACKS:
        if a == "none":
            continue
        for g in AGGS:
            expect = round(matrix["none"][g] - matrix[a][g], 4)
            assert success["delta_top1"][a][g] == pytest.approx(expect)


@pytest.mark.parametrize("seed", [2, 3])
def test_seed_replication_passes_gate(seed):
    """The seed-2/3 reruns (results/matrix_s2, _s3) must satisfy the same
    expectation table — the gate's floors are set below the THREE-seed
    measured range — and must replicate the ALIE band_rel damage that
    justifies the relative rule."""
    from examples.robustness_matrix import evaluate_expectations

    d = os.path.join(REPO, "results", f"matrix_s{seed}")
    if not os.path.exists(os.path.join(d, "matrix.json")):
        pytest.skip(f"no committed seed-{seed} matrix")
    with open(os.path.join(d, "matrix.json")) as f:
        m = json.load(f)
    assert m["_seed"] == seed
    rows, ok = evaluate_expectations(m)
    assert ok, [r for r in rows if not r["ok"]]
    with open(os.path.join(d, "summary.json")) as f:
        s = json.load(f)
    assert s["all_ok"] and s["seed"] == seed
    # full per-cell sync incl. the rule fields — a re-tuned EXPECTATIONS
    # table with a stale seed-N summary must fail here, not pass silently
    recorded = {(r["attack"], r["agg"]): r for r in s["cells"]}
    for r in rows:
        rec = recorded[(r["attack"], r["agg"])]
        assert rec["top1"] == pytest.approx(r["top1"])
        assert rec["ok"] == r["ok"]
        assert rec["rule"] == r["rule"]
    for g in ("median", "trimmedmean"):
        assert m["none"][g] - m["alie"][g] >= 0.05
