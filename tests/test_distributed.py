"""Multi-host plumbing tests on the 8-device virtual CPU mesh.

The reference's only multi-node test story is "deploy a Ray cluster"
(README.rst:146-149); here the distributed layer is exercised in-process:
hybrid mesh construction, host client-range computation, process-local
array assembly, and a full sharded round over a distributed-built mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.parallel import distributed as dist
from blades_tpu.parallel.mesh import CLIENTS_AXIS, MODEL_AXIS, make_plan


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or try to contact a coordinator
    assert dist.is_coordinator()


def test_make_global_mesh_default():
    mesh = dist.make_global_mesh()
    assert mesh.shape[CLIENTS_AXIS] == 8
    assert mesh.shape[MODEL_AXIS] == 1


def test_make_global_mesh_2d():
    mesh = dist.make_global_mesh(mesh_shape=(4, 2))
    assert mesh.shape[CLIENTS_AXIS] == 4
    assert mesh.shape[MODEL_AXIS] == 2
    with pytest.raises(ValueError):
        dist.make_global_mesh(mesh_shape=(3, 2))


def test_hybrid_mesh_two_slices():
    """Treat the 8 CPU devices as 2 'slices' of 4: outer DCN axis on
    clients, inner ICI axis on model."""
    mesh = dist.make_global_mesh(
        mesh_shape=(2, 2), dcn_mesh_shape=(2, 1)
    )
    assert mesh.shape[CLIENTS_AXIS] == 4  # 2 dcn x 2 ici
    assert mesh.shape[MODEL_AXIS] == 2
    # a psum over the hybrid mesh must see every device exactly once
    plan = make_plan(mesh)
    x = jax.device_put(jnp.ones((8, 4)), plan.clients)
    total = jax.jit(lambda a: jnp.sum(a))(x)
    assert float(total) == 32.0


def test_host_client_slice_single_host_covers_all():
    mesh = dist.make_global_mesh()
    lo, hi = dist.host_client_slice(16, mesh)
    assert (lo, hi) == (0, 16)  # one process owns every shard
    with pytest.raises(ValueError):
        dist.host_client_slice(9, mesh)


def test_make_global_client_array_roundtrip():
    mesh = dist.make_global_mesh()
    plan = make_plan(mesh)
    rows = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    lo, hi = dist.host_client_slice(16, mesh)
    arr = dist.make_global_client_array(rows[lo:hi], 16, plan)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), rows)
    assert arr.sharding.spec == plan.clients.spec


def test_round_on_distributed_mesh():
    """One engine round over a make_global_mesh-built hybrid mesh."""
    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.core import RoundEngine
    from blades_tpu.models import create_model
    from blades_tpu.models.common import build_fns

    mesh = dist.make_global_mesh(mesh_shape=(2, 2), dcn_mesh_shape=(2, 1))
    plan = make_plan(mesh)
    spec = build_fns(create_model("mlp"), (28, 28, 1))
    params = spec.init(jax.random.PRNGKey(0))
    engine = RoundEngine(
        spec.train_loss_fn,
        spec.eval_logits_fn,
        params,
        num_clients=8,
        aggregator=get_aggregator("trimmedmean"),
        plan=plan,
    )
    state = engine.init(params)
    kd = jax.random.PRNGKey(1)
    cx = jax.device_put(
        jax.random.normal(kd, (8, 1, 4, 28, 28, 1)), plan.clients
    )
    cy = jax.device_put(
        jax.random.randint(jax.random.fold_in(kd, 1), (8, 1, 4), 0, 10),
        plan.clients,
    )
    state, m = engine.run_round(state, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    assert np.isfinite(float(m.train_loss))
    dist.sync_global_devices("test")  # single-host barrier must be a no-op


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("nproc,devs", [(2, 4), (4, 2)])
def test_cross_process_cluster_runs_sharded_round(nproc, devs):
    """REAL cross-process execution: ``nproc`` subprocesses x ``devs``
    virtual CPU devices join one jax.distributed cluster
    (explicit-coordinator branch, parallel/distributed.py:56-61) and run one
    sharded federated round end-to-end through host_client_slice +
    make_global_client_array. The 4x2 topology exercises a clients axis
    spanning four process boundaries. All processes must see the same
    8-device global mesh and produce identical round metrics, which must
    also match a single-process run of the same workload."""
    from blades_tpu.parallel._dist_worker import run_local_cluster

    try:
        results = run_local_cluster(nproc, devs, timeout=600)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            # some jaxlib builds ship a CPU backend without cross-process
            # collectives; the topology logic is still covered by the
            # in-process mesh tests above
            pytest.skip("this jaxlib's CPU backend lacks multiprocess "
                        "collectives")
        raise
    assert set(results) == set(range(nproc)), f"missing results: {results}"

    for pid, r in results.items():
        assert r["num_processes"] == nproc
        assert r["local_devices"] == devs
        assert r["global_devices"] == 8
        assert np.isfinite(r["train_loss"])
        assert r["is_coordinator"] == (pid == 0)
        # each host materialized only its own contiguous client block
        per = 16 // nproc
        assert r["client_slice"] == [pid * per, (pid + 1) * per]
        # SPMD: every process computed the same global round
        assert r["train_loss"] == pytest.approx(results[0]["train_loss"])
        assert r["agg_norm"] == pytest.approx(results[0]["agg_norm"])

    # cross-check against the same workload in THIS process (8 local devices)
    from blades_tpu.parallel._dist_worker import make_data, run_round

    mesh = dist.make_global_mesh((8, 1))
    plan = make_plan(mesh)
    cx, cy = make_data(16, 2, 4)
    m = run_round(
        plan,
        16,
        jax.device_put(jnp.asarray(cx), plan.clients),
        jax.device_put(jnp.asarray(cy), plan.clients),
        num_byzantine=4,
    )
    assert results[0]["train_loss"] == pytest.approx(
        float(m.train_loss), rel=1e-5
    )
    assert results[0]["agg_norm"] == pytest.approx(float(m.agg_norm), rel=1e-4)


def test_worker_failure_fails_fast_and_reaps():
    """Kill one worker mid-flight: the harness must report the dead worker
    promptly (its peer is stuck at the cluster barrier and would otherwise
    hang out the full timeout) and leave no orphan processes behind
    (``_dist_worker.py`` reaping branch)."""
    import time

    from blades_tpu.parallel._dist_worker import run_local_cluster

    spawned = []

    def injector(procs):
        spawned.extend(procs)
        time.sleep(3)  # let the cluster begin joining, then lose a worker
        procs[1].kill()

    t0 = time.time()
    with pytest.raises(RuntimeError, match=r"worker 1 failed \(rc=-9\)"):
        run_local_cluster(2, 4, timeout=420, _fault_injector=injector)
    # fail-fast: bounded by the kill delay + poll cadence, not the timeout
    assert time.time() - t0 < 120
    # every spawned worker reaped — no orphan holding devices (or a TPU
    # lease); checked on THIS run's Popen handles, not machine-wide pgrep
    assert len(spawned) == 2
    assert all(p.poll() is not None for p in spawned), "unreaped workers"


def test_initialize_warns_on_coordinator_failure(monkeypatch):
    """Autodetect failures other than 'no cluster found' must warn loudly
    instead of silently degrading a multi-host job to single-host."""
    import warnings

    def boom(**kw):
        raise RuntimeError("connection to coordinator 10.0.0.1:1234 timed out")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.warns(RuntimeWarning, match="coordinator"):
        dist.initialize()

    # the genuine no-cluster case stays quiet
    def no_cluster(**kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", no_cluster)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()

    # explicit args must re-raise, not warn
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError):
        dist.initialize(coordinator_address="10.0.0.1:1234", num_processes=2,
                        process_id=0)


def test_initialize_late_call_classification(monkeypatch):
    """The late-call hazard (backend touched before initialize): quiet no-op
    in a plain single-host process, but a HARD error when multi-host cluster
    env hints are present — warn-and-degrade there would silently fracture a
    pod into independent single-host trainings (VERDICT r4 weak #4)."""
    import warnings

    def late(**kw):
        raise RuntimeError(
            "jax.distributed.initialize() must be called before any JAX "
            "calls that might initialize the XLA backend"
        )

    monkeypatch.setattr(jax.distributed, "initialize", late)
    for v in dist._CLUSTER_ENV_VARS:
        monkeypatch.delenv(v, raising=False)

    # no cluster hints: harmless (tests, notebooks) — stays quiet
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()

    # a SINGLE-host TPU_WORKER_HOSTNAMES (axon tunnel exports
    # 'localhost' in every python process) is not a pod — stays quiet
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()

    # cluster hints present: must raise, naming the offending variable
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    with pytest.raises(RuntimeError, match="TPU_WORKER_HOSTNAMES"):
        dist.initialize()

    # the "backend already initialized" message class must classify the
    # same way — it contains "already initialized", so it would be
    # swallowed by the double-call no-op branch if checked in the wrong
    # order
    def late_backend(**kw):
        raise RuntimeError("backend already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", late_backend)
    with pytest.raises(RuntimeError, match="TPU_WORKER_HOSTNAMES"):
        dist.initialize()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()  # no hints: quiet no-op
