"""Multi-host plumbing tests on the 8-device virtual CPU mesh.

The reference's only multi-node test story is "deploy a Ray cluster"
(README.rst:146-149); here the distributed layer is exercised in-process:
hybrid mesh construction, host client-range computation, process-local
array assembly, and a full sharded round over a distributed-built mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.parallel import distributed as dist
from blades_tpu.parallel.mesh import CLIENTS_AXIS, MODEL_AXIS, make_plan


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or try to contact a coordinator
    assert dist.is_coordinator()


def test_make_global_mesh_default():
    mesh = dist.make_global_mesh()
    assert mesh.shape[CLIENTS_AXIS] == 8
    assert mesh.shape[MODEL_AXIS] == 1


def test_make_global_mesh_2d():
    mesh = dist.make_global_mesh(mesh_shape=(4, 2))
    assert mesh.shape[CLIENTS_AXIS] == 4
    assert mesh.shape[MODEL_AXIS] == 2
    with pytest.raises(ValueError):
        dist.make_global_mesh(mesh_shape=(3, 2))


def test_hybrid_mesh_two_slices():
    """Treat the 8 CPU devices as 2 'slices' of 4: outer DCN axis on
    clients, inner ICI axis on model."""
    mesh = dist.make_global_mesh(
        mesh_shape=(2, 2), dcn_mesh_shape=(2, 1)
    )
    assert mesh.shape[CLIENTS_AXIS] == 4  # 2 dcn x 2 ici
    assert mesh.shape[MODEL_AXIS] == 2
    # a psum over the hybrid mesh must see every device exactly once
    plan = make_plan(mesh)
    x = jax.device_put(jnp.ones((8, 4)), plan.clients)
    total = jax.jit(lambda a: jnp.sum(a))(x)
    assert float(total) == 32.0


def test_host_client_slice_single_host_covers_all():
    mesh = dist.make_global_mesh()
    lo, hi = dist.host_client_slice(16, mesh)
    assert (lo, hi) == (0, 16)  # one process owns every shard
    with pytest.raises(ValueError):
        dist.host_client_slice(9, mesh)


def test_make_global_client_array_roundtrip():
    mesh = dist.make_global_mesh()
    plan = make_plan(mesh)
    rows = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    lo, hi = dist.host_client_slice(16, mesh)
    arr = dist.make_global_client_array(rows[lo:hi], 16, plan)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), rows)
    assert arr.sharding.spec == plan.clients.spec


def test_round_on_distributed_mesh():
    """One engine round over a make_global_mesh-built hybrid mesh."""
    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.core import RoundEngine
    from blades_tpu.models import create_model
    from blades_tpu.models.common import build_fns

    mesh = dist.make_global_mesh(mesh_shape=(2, 2), dcn_mesh_shape=(2, 1))
    plan = make_plan(mesh)
    spec = build_fns(create_model("mlp"), (28, 28, 1))
    params = spec.init(jax.random.PRNGKey(0))
    engine = RoundEngine(
        spec.train_loss_fn,
        spec.eval_logits_fn,
        params,
        num_clients=8,
        aggregator=get_aggregator("trimmedmean"),
        plan=plan,
    )
    state = engine.init(params)
    kd = jax.random.PRNGKey(1)
    cx = jax.device_put(
        jax.random.normal(kd, (8, 1, 4, 28, 28, 1)), plan.clients
    )
    cy = jax.device_put(
        jax.random.randint(jax.random.fold_in(kd, 1), (8, 1, 4), 0, 10),
        plan.clients,
    )
    state, m = engine.run_round(state, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    assert np.isfinite(float(m.train_loss))
    dist.sync_global_devices("test")  # single-host barrier must be a no-op


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster_runs_sharded_round():
    """REAL cross-process execution: 2 subprocesses x 4 virtual CPU devices
    join one jax.distributed cluster (explicit-coordinator branch,
    parallel/distributed.py:56-61) and run one sharded federated round
    end-to-end through host_client_slice + make_global_client_array. Both
    processes must see the same 8-device global mesh and produce identical
    round metrics, which must also match a single-process run of the same
    workload."""
    import os
    import subprocess
    import sys

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "blades_tpu.parallel._dist_worker",
                str(pid),
                "2",
                str(port),
                "4",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    results = {}
    for pid, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {pid} timed out")
        assert p.returncode == 0, f"worker {pid} failed:\n{err[-2000:]}"
        for line in out.splitlines():
            if line.startswith("DIST_RESULT "):
                results[pid] = __import__("json").loads(
                    line[len("DIST_RESULT "):]
                )
    assert set(results) == {0, 1}, f"missing worker results: {results}"

    for pid, r in results.items():
        assert r["num_processes"] == 2
        assert r["local_devices"] == 4
        assert r["global_devices"] == 8
        assert np.isfinite(r["train_loss"])
    assert results[0]["is_coordinator"] and not results[1]["is_coordinator"]
    # each host materialized only its own half of the client population
    assert results[0]["client_slice"] == [0, 8]
    assert results[1]["client_slice"] == [8, 16]
    # SPMD: both processes computed the same global round
    assert results[0]["train_loss"] == pytest.approx(results[1]["train_loss"])
    assert results[0]["agg_norm"] == pytest.approx(results[1]["agg_norm"])

    # cross-check against the same workload in THIS process (8 local devices)
    from blades_tpu.parallel._dist_worker import make_data, run_round

    mesh = dist.make_global_mesh((8, 1))
    plan = make_plan(mesh)
    cx, cy = make_data(16, 2, 4)
    m = run_round(
        plan,
        16,
        jax.device_put(jnp.asarray(cx), plan.clients),
        jax.device_put(jnp.asarray(cy), plan.clients),
        num_byzantine=4,
    )
    assert results[0]["train_loss"] == pytest.approx(
        float(m.train_loss), rel=1e-5
    )
    assert results[0]["agg_norm"] == pytest.approx(float(m.agg_norm), rel=1e-4)


def test_initialize_warns_on_coordinator_failure(monkeypatch):
    """Autodetect failures other than 'no cluster found' must warn loudly
    instead of silently degrading a multi-host job to single-host."""
    import warnings

    def boom(**kw):
        raise RuntimeError("connection to coordinator 10.0.0.1:1234 timed out")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.warns(RuntimeWarning, match="coordinator"):
        dist.initialize()

    # the genuine no-cluster case stays quiet
    def no_cluster(**kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", no_cluster)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()

    # explicit args must re-raise, not warn
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError):
        dist.initialize(coordinator_address="10.0.0.1:1234", num_processes=2,
                        process_id=0)
