"""Multi-host plumbing tests on the 8-device virtual CPU mesh.

The reference's only multi-node test story is "deploy a Ray cluster"
(README.rst:146-149); here the distributed layer is exercised in-process:
hybrid mesh construction, host client-range computation, process-local
array assembly, and a full sharded round over a distributed-built mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blades_tpu.parallel import distributed as dist
from blades_tpu.parallel.mesh import CLIENTS_AXIS, MODEL_AXIS, make_plan


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or try to contact a coordinator
    assert dist.is_coordinator()


def test_make_global_mesh_default():
    mesh = dist.make_global_mesh()
    assert mesh.shape[CLIENTS_AXIS] == 8
    assert mesh.shape[MODEL_AXIS] == 1


def test_make_global_mesh_2d():
    mesh = dist.make_global_mesh(mesh_shape=(4, 2))
    assert mesh.shape[CLIENTS_AXIS] == 4
    assert mesh.shape[MODEL_AXIS] == 2
    with pytest.raises(ValueError):
        dist.make_global_mesh(mesh_shape=(3, 2))


def test_hybrid_mesh_two_slices():
    """Treat the 8 CPU devices as 2 'slices' of 4: outer DCN axis on
    clients, inner ICI axis on model."""
    mesh = dist.make_global_mesh(
        mesh_shape=(2, 2), dcn_mesh_shape=(2, 1)
    )
    assert mesh.shape[CLIENTS_AXIS] == 4  # 2 dcn x 2 ici
    assert mesh.shape[MODEL_AXIS] == 2
    # a psum over the hybrid mesh must see every device exactly once
    plan = make_plan(mesh)
    x = jax.device_put(jnp.ones((8, 4)), plan.clients)
    total = jax.jit(lambda a: jnp.sum(a))(x)
    assert float(total) == 32.0


def test_host_client_slice_single_host_covers_all():
    mesh = dist.make_global_mesh()
    lo, hi = dist.host_client_slice(16, mesh)
    assert (lo, hi) == (0, 16)  # one process owns every shard
    with pytest.raises(ValueError):
        dist.host_client_slice(9, mesh)


def test_make_global_client_array_roundtrip():
    mesh = dist.make_global_mesh()
    plan = make_plan(mesh)
    rows = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    lo, hi = dist.host_client_slice(16, mesh)
    arr = dist.make_global_client_array(rows[lo:hi], 16, plan)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), rows)
    assert arr.sharding.spec == plan.clients.spec


def test_round_on_distributed_mesh():
    """One engine round over a make_global_mesh-built hybrid mesh."""
    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.core import RoundEngine
    from blades_tpu.models import create_model
    from blades_tpu.models.common import build_fns

    mesh = dist.make_global_mesh(mesh_shape=(2, 2), dcn_mesh_shape=(2, 1))
    plan = make_plan(mesh)
    spec = build_fns(create_model("mlp"), (28, 28, 1))
    params = spec.init(jax.random.PRNGKey(0))
    engine = RoundEngine(
        spec.train_loss_fn,
        spec.eval_logits_fn,
        params,
        num_clients=8,
        aggregator=get_aggregator("trimmedmean"),
        plan=plan,
    )
    state = engine.init(params)
    kd = jax.random.PRNGKey(1)
    cx = jax.device_put(
        jax.random.normal(kd, (8, 1, 4, 28, 28, 1)), plan.clients
    )
    cy = jax.device_put(
        jax.random.randint(jax.random.fold_in(kd, 1), (8, 1, 4), 0, 10),
        plan.clients,
    )
    state, m = engine.run_round(state, cx, cy, 0.1, 1.0, jax.random.PRNGKey(2))
    assert np.isfinite(float(m.train_loss))
    dist.sync_global_devices("test")  # single-host barrier must be a no-op


def test_initialize_warns_on_coordinator_failure(monkeypatch):
    """Autodetect failures other than 'no cluster found' must warn loudly
    instead of silently degrading a multi-host job to single-host."""
    import warnings

    def boom(**kw):
        raise RuntimeError("connection to coordinator 10.0.0.1:1234 timed out")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.warns(RuntimeWarning, match="coordinator"):
        dist.initialize()

    # the genuine no-cluster case stays quiet
    def no_cluster(**kw):
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", no_cluster)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        dist.initialize()

    # explicit args must re-raise, not warn
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError):
        dist.initialize(coordinator_address="10.0.0.1:1234", num_processes=2,
                        process_id=0)
