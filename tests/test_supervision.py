"""Run-supervisor tests (``blades_tpu/supervision``): heartbeat watchdog,
group-kill primitives, degrade-and-resume policies, and the end-to-end
acceptance scenario — a supervised Simulator hung mid-run is detected via
heartbeat staleness, its whole process group reaped (zero orphans), and
the relaunch resumes bit-exactly from the per-round checkpoint, with the
attempt/kill/resume trail in ``telemetry.jsonl``.

All tier-1: the hung children are ``sleep``-based stubs (no TPU, and no
jax import in the fast tests); the one real-Simulator scenario runs the
chaos child (``scripts/chaos.py``) on a single virtual CPU device.

Reference counterpart: none — the reference delegates process lifetime to
an assumed-healthy Ray cluster (``src/blades/simulator.py:189-211``).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from blades_tpu.supervision import heartbeat as hb
from blades_tpu.supervision.supervisor import (
    POLICIES,
    Supervisor,
    kill_process_group,
    list_group,
    resolve_policy,
    supervise,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS = os.path.join(REPO, "scripts", "chaos.py")


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _sup_events(path):
    return [r for r in _records(path) if r.get("t") == "supervisor"]


# ------------------------------------------------------------ heartbeat file


def test_beat_noop_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv(hb.HEARTBEAT_ENV, raising=False)
    hb.beat(round_idx=1)  # must not raise, must not create anything
    f = tmp_path / "hb"
    hb.beat(round_idx=3, path=str(f))
    rec = hb.read(str(f))
    assert rec["t"] == "heartbeat" and rec["round"] == 3
    assert hb.age_s(str(f)) < 5.0
    assert hb.age_s(str(tmp_path / "missing")) is None


def test_beat_env_path(tmp_path, monkeypatch):
    f = tmp_path / "hb"
    monkeypatch.setenv(hb.HEARTBEAT_ENV, str(f))
    hb.beat(round_idx=7)
    assert hb.read(str(f))["round"] == 7


def test_beat_never_raises_on_bad_path(monkeypatch):
    monkeypatch.setenv(hb.HEARTBEAT_ENV, "/proc/definitely/not/writable/hb")
    hb.beat(round_idx=1)  # swallowed OSError


# --------------------------------------------------------- group primitives


def test_kill_process_group_reaps_grandchildren():
    """A SIGTERM-ignoring child that spawned a grandchild: the whole group
    dies and a pgid scan finds zero survivors (the orphaned-grandchild
    wedge from ADVICE.md medium #1)."""
    p = subprocess.Popen(
        ["/bin/sh", "-c", "trap '' TERM; sleep 600 & sleep 600"],
        start_new_session=True,
    )
    pgid = os.getpgid(p.pid)
    time.sleep(0.3)  # let the grandchild spawn
    assert len(list_group(pgid)) >= 2
    t0 = time.monotonic()
    info = kill_process_group(p, term_grace_s=0.5)
    assert time.monotonic() - t0 < 15.0
    assert info["escalated"] is True  # TERM was trapped; KILL was needed
    assert info["survivors"] == []
    assert list_group(pgid) == []


def test_kill_process_group_graceful_term():
    p = subprocess.Popen(["sleep", "600"], start_new_session=True)
    info = kill_process_group(p, term_grace_s=5.0)
    assert info["escalated"] is False  # sleep dies on TERM
    assert info["survivors"] == []


def test_sigstopped_child_still_killed():
    """SIGSTOP'd processes cannot run TERM handlers; the escalation must
    still remove them (SIGKILL acts on stopped processes)."""
    p = subprocess.Popen(["sleep", "600"], start_new_session=True)
    os.kill(p.pid, signal.SIGSTOP)
    info = kill_process_group(p, term_grace_s=0.3)
    assert info["survivors"] == []
    assert p.poll() is not None


# ------------------------------------------------------------- the watchdog


def test_hung_child_killed_within_staleness_window(tmp_path):
    """Satellite: a deliberately-hung child (never beats) is killed
    group-wide within the startup-grace window, grandchild included."""
    telem = tmp_path / "telemetry.jsonl"
    sup = Supervisor(
        ["/bin/sh", "-c", "sleep 600 & sleep 600"],
        heartbeat_timeout_s=0.5, startup_grace_s=1.0, attempts=1,
        term_grace_s=0.5, poll_s=0.1, telemetry_path=str(telem),
        heartbeat_file=str(tmp_path / "hb"),
    )
    t0 = time.monotonic()
    result = sup.run()
    assert time.monotonic() - t0 < 20.0
    assert not result.ok
    (attempt,) = result.attempts
    assert attempt.reason == "startup_stale"
    assert attempt.survivors == ()  # zero orphans, asserted via pgid scan
    kills = [e for e in _sup_events(str(telem)) if e["event"] == "kill"]
    assert len(kills) == 1 and kills[0]["survivors"] == []


def test_stale_after_beats_triggers_heartbeat_kill(tmp_path):
    """A child that beats, then hangs: the kill reason is heartbeat
    staleness (not startup), and the last beaten round is recorded."""
    beat_then_hang = (
        "import sys, time; sys.path.insert(0, %r); "
        "from blades_tpu.supervision.heartbeat import beat; "
        "beat(round_idx=2); time.sleep(600)" % REPO
    )
    telem = tmp_path / "telemetry.jsonl"
    result = supervise(
        [sys.executable, "-c", beat_then_hang],
        heartbeat_timeout_s=1.0, startup_grace_s=30.0, attempts=1,
        term_grace_s=0.5, poll_s=0.1, telemetry_path=str(telem),
        heartbeat_file=str(tmp_path / "hb"),
    )
    (attempt,) = result.attempts
    assert attempt.reason == "heartbeat_stale"
    (kill,) = [e for e in _sup_events(str(telem)) if e["event"] == "kill"]
    assert kill["last_round"] == 2


def test_beating_child_survives(tmp_path):
    code = (
        "import sys, time; sys.path.insert(0, %r); "
        "from blades_tpu.supervision.heartbeat import beat\n"
        "for i in range(5): time.sleep(0.3); beat(round_idx=i)" % REPO
    )
    result = supervise(
        [sys.executable, "-c", code],
        heartbeat_timeout_s=1.0, startup_grace_s=30.0, attempts=1,
        poll_s=0.1, heartbeat_file=str(tmp_path / "hb"),
    )
    assert result.ok and result.attempts[0].reason == "exit"


# ------------------------------------------------- degrade & resume policies


def test_degrade_ladder_cumulative_and_resume_env(tmp_path):
    """Attempt 1 runs clean; attempt 2 adds policy 1; attempt 3 adds policy
    2 on top — and every relaunch exports BLADES_RESUME=1."""
    probe = tmp_path / "attempts.jsonl"
    code = (
        "import json, os, sys\n"
        "with open(%r, 'a') as f:\n"
        "    f.write(json.dumps({k: os.environ.get(k) for k in\n"
        "        ('JAX_PLATFORMS', 'BLADES_TPU_NO_PALLAS', 'BLADES_RESUME',\n"
        "         'BLADES_SUPERVISED')}) + '\\n')\n"
        "sys.exit(1)" % str(probe)
    )
    result = supervise(
        [sys.executable, "-c", code],
        attempts=3, base_delay_s=0.01, poll_s=0.05,
        degrade=["single_device", "no_pallas"],
        heartbeat_file=str(tmp_path / "hb"),
        telemetry_path=str(tmp_path / "telemetry.jsonl"),
    )
    assert not result.ok
    rows = _records(str(probe))
    assert len(rows) == 3
    assert rows[0]["BLADES_SUPERVISED"] == "1"
    assert rows[0]["BLADES_RESUME"] is None and rows[0]["BLADES_TPU_NO_PALLAS"] is None
    assert rows[1]["BLADES_RESUME"] == "1"
    assert rows[1]["JAX_PLATFORMS"] == "cpu"  # single_device applied
    assert rows[1]["BLADES_TPU_NO_PALLAS"] is None  # ladder, not all-at-once
    assert rows[2]["BLADES_TPU_NO_PALLAS"] == "1"  # cumulative
    events = _sup_events(str(tmp_path / "telemetry.jsonl"))
    assert [e["event"] for e in events if e["event"] in
            ("degrade", "give_up")].count("degrade") == 2
    assert result.attempts[2].degrade == ("single_device", "no_pallas")


def test_policy_resolution():
    assert resolve_policy("no_pallas") is POLICIES["no_pallas"]
    custom = resolve_policy({"FOO": "1"})
    assert custom.env == {"FOO": "1"}
    with pytest.raises(ValueError, match="unknown degrade policy"):
        resolve_policy("warp_speed")


def test_backoff_shared_with_retry():
    from blades_tpu.utils.retry import backoff_delay

    assert [backoff_delay(i, 1.0, 60.0) for i in (1, 2, 3, 7)] == [
        1.0, 2.0, 4.0, 60.0]


def test_success_passthrough_single_json_line(tmp_path):
    """bench.py's one-JSON-line contract survives supervision: the child's
    stdout is inherited, supervisor diagnostics go to stderr only."""
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision", "--attempts", "2",
         "--deadline", "60", "--", sys.executable, "-c",
         "print('{\"metric\": \"x\", \"value\": 1.0}')"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 0, p.stderr
    assert p.stdout.strip() == '{"metric": "x", "value": 1.0}'
    assert "[supervisor]" in p.stderr


def test_cli_requires_command():
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision", "--attempts", "1"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 2
    assert "no workload command" in p.stderr


def test_cli_rejects_unknown_degrade_policy():
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision",
         "--degrade", "single-device", "--", "true"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 2  # argparse usage error, not a raw traceback
    assert "unknown --degrade policy" in p.stderr
    assert "Traceback" not in p.stderr


def test_unlaunchable_workload_terminates_trail_cleanly(tmp_path):
    """A bad argv must not crash the supervisor: the trail ends with
    launch_failed + give_up and the result reports rc 127."""
    telem = tmp_path / "telemetry.jsonl"
    result = supervise(
        ["/definitely/not/a/binary-xyz"], attempts=3,
        telemetry_path=str(telem), heartbeat_file=str(tmp_path / "hb"),
    )
    assert not result.ok and result.returncode == 127
    (attempt,) = result.attempts  # no retries: unlaunchable is not transient
    assert attempt.reason == "launch_failed"
    kinds = [e["event"] for e in _sup_events(str(telem))]
    assert kinds[-2:] == ["launch_failed", "give_up"]


def test_cli_never_exits_zero_on_give_up():
    """A child trapping SIGTERM to exit 0 must not turn a given-up
    supervision into CLI success."""
    p = subprocess.run(
        [sys.executable, "-m", "blades_tpu.supervision", "--attempts", "1",
         "--deadline", "0.5", "--poll", "0.1", "--term-grace", "5", "--",
         "/bin/sh", "-c", "trap 'exit 0' TERM; sleep 600"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert p.returncode == 1, (p.returncode, p.stderr)


def test_killed_final_attempt_reports_real_signal(tmp_path):
    """A child that honors the graceful SIGTERM yields returncode -15, not
    a blanket SIGKILL report (callers script on 128+signal)."""
    result = supervise(
        ["sleep", "600"], deadline_s=0.5, attempts=1, poll_s=0.1,
        term_grace_s=5.0, heartbeat_file=str(tmp_path / "hb"),
    )
    assert not result.ok
    assert result.returncode == -signal.SIGTERM
    assert result.attempts[0].reason == "deadline"


def test_fresh_unsupervised_run_starts_a_new_trace(tmp_path, monkeypatch):
    """The log-dir wipe preserves telemetry.jsonl for kill->relaunch
    post-mortems, but a FRESH unsupervised run on the same log_path is a
    new experiment: per-run consumers (trace_summary, chaos invariant
    counts) must not see the previous run's records. Supervised attempt 1
    must NOT truncate (the supervisor's launch record is already there)."""
    import json as _json

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    monkeypatch.delenv(hb.SUPERVISED_ENV, raising=False)
    log = str(tmp_path / "run")
    kw = dict(global_rounds=1, local_steps=1, train_batch_size=8,
              validate_interval=1)

    def one_run():
        Simulator(
            dataset=Synthetic(num_clients=4, train_size=80, test_size=40,
                              cache=False),
            log_path=log, seed=0,
        ).run("mlp", **kw)

    one_run()
    one_run()  # fresh rerun: trace restarts
    recs = [_json.loads(l) for l in open(os.path.join(log, "telemetry.jsonl"))]
    assert sum(1 for r in recs if r.get("t") == "round") == 1
    assert sum(1 for r in recs if r.get("t") == "meta") == 1

    monkeypatch.setenv(hb.SUPERVISED_ENV, "1")
    one_run()  # supervised attempt: appends, never truncates
    recs = [_json.loads(l) for l in open(os.path.join(log, "telemetry.jsonl"))]
    assert sum(1 for r in recs if r.get("t") == "round") == 2


# ------------------------------------------- end-to-end: hang, kill, resume


def test_supervised_simulator_hang_is_killed_and_resumes_bit_exact(tmp_path):
    """Acceptance: a supervised run whose child hangs hard at round 2
    (spawning a grandchild first) is detected via heartbeat staleness, the
    whole process group is reaped (zero orphans), and the relaunch resumes
    from the per-round checkpoint producing bit-identical final parameters
    to an uninterrupted run — trail in telemetry.jsonl."""
    env = dict(os.environ, CHAOS_DEVICES="1")
    env.pop(hb.HEARTBEAT_ENV, None)

    # uninterrupted reference (same scenario seed, fresh log dir)
    ref_out = tmp_path / "ref"
    ref_params = tmp_path / "ref_params.npy"
    p = subprocess.run(
        [sys.executable, CHAOS, "--child", "--seed", "0",
         "--out", str(ref_out), "--params-out", str(ref_params)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420,
    )
    assert p.returncode == 0, (p.stdout, p.stderr)
    assert "CHAOS_RESULT" in p.stdout

    # supervised run: hangs at round 2, exactly once
    sup_out = tmp_path / "sup"
    sup_params = tmp_path / "sup_params.npy"
    telem = str(sup_out / "telemetry.jsonl")
    sup = Supervisor(
        [sys.executable, CHAOS, "--child", "--seed", "0",
         "--out", str(sup_out), "--params-out", str(sup_params),
         "--hang-at", "2"],
        heartbeat_timeout_s=6.0, startup_grace_s=300.0, attempts=2,
        base_delay_s=0.1, term_grace_s=5.0, poll_s=0.2,
        telemetry_path=telem, heartbeat_file=str(tmp_path / "hb"),
        env={"CHAOS_DEVICES": "1"}, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    result = sup.run()
    assert result.ok, result
    assert len(result.attempts) == 2
    first, second = result.attempts
    assert first.reason == "heartbeat_stale"
    assert first.survivors == ()  # grandchild `sleep 600` reaped too
    assert second.reason == "exit" and second.resumed

    # bit-exact resume
    ref = np.load(ref_params)
    out = np.load(sup_params)
    np.testing.assert_array_equal(ref, out)

    # the attempt/kill/resume trail is in the run's own telemetry.jsonl
    events = _sup_events(telem)
    kinds = [e["event"] for e in events]
    for expected in ("launch", "kill", "retry", "launch", "complete"):
        assert expected in kinds, kinds
    (kill,) = [e for e in events if e["event"] == "kill"]
    assert kill["reason"] == "heartbeat_stale"
    assert kill["survivors"] == []
    # the hang fires in round 2's on_round_end, BEFORE round 2's flush/beat
    # — so the last recorded liveness is round 1's beat
    assert kill["last_round"] == 1
    launches = [e for e in events if e["event"] == "launch"]
    assert launches[0]["resume"] is False and launches[1]["resume"] is True
    # the child's own records interleave in the same trace: attempt 1
    # flushed round 1, then hung in round 2's on_round_end — round 2's
    # completed STATE rode the crash autosave (so the resumed attempt
    # starts at round 3), but its round record was lost to the kill
    rounds = [r for r in _records(telem) if r.get("t") == "round"]
    assert {r["round"] for r in rounds} == {1, 3}
    # SIGTERM reached the hung-in-Python child first: the crash autosave
    # trail shows the graceful half of the escalation fired
    crash = [r for r in _records(telem) if r.get("t") == "crash_checkpoint"]
    assert crash and crash[0]["round"] == 2
    assert "SupervisorTermination" in crash[0]["error"]
