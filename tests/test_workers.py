"""Worker-process execution pool (`blades_tpu/service/workers.py` +
`worker.py`, server integration in `server.py::_work_pool`): crash/hang
containment with parent-enforced (SIGALRM-free) deadlines.

The acceptance invariants, each against a REAL `serve.py start
--workers N` subprocess (probe-only, jax-free, server up in ~1s):

- pool spawn → shutdown leaves ZERO orphans (a ``/proc`` scan over
  every process group the pool ever spawned);
- SIGKILL a busy worker mid-request: the server stays up, the
  replacement executes ONLY the unjournaled cells, and the reply is
  content-identical to an undisturbed run (the PR 13 resume invariant,
  via worker death instead of server death);
- a worker hung past its per-cell deadline is reaped by the PARENT's
  group-kill ladder — no SIGALRM anywhere — and the retry completes;
- warm-affinity routing: a repeat request lands on the worker that
  already served its body (per-worker warm sets, scheduler pass 1);
- ``--workers 0`` falls back to the PR 17 in-process path with an
  identical client-visible reply and an unchanged status surface;
- the `deadline_unenforced` note (the satellite fix for the silent
  SIGALRM hole in `sweeps/resilient.py`) fires exactly once from a
  non-main-thread alarm caller, is suppressed under
  ``deadline="external"``, and surfaces in `sweep_status.py`.

Reference counterpart: Ray's actor supervision in
``src/blades/simulator.py`` — actor death is handled by the framework
there; here every containment claim is measured.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.service.client import ServiceClient, ServiceError  # noqa: E402
from blades_tpu.service.protocol import socket_path_for  # noqa: E402
from blades_tpu.service.workers import WorkerPool  # noqa: E402

SERVE = os.path.join(REPO, "scripts", "serve.py")


def _start(tmp_path, name, *extra, env=None):
    out = str(tmp_path / name)
    e = dict(os.environ, BLADES_LEDGER=str(tmp_path / f"{name}_ledger.jsonl"))
    e.update(env or {})
    proc = subprocess.Popen(
        [sys.executable, SERVE, "start", "--out", out,
         "--base-delay", "0.05", *extra],
        env=e, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    client = ServiceClient(
        socket_path_for(out), timeout=60,
        connect_retries=50, connect_delay_s=0.2,
    )
    return out, proc, client


def _finish(proc, client):
    try:
        if proc.poll() is None:
            client.drain()
    except ServiceError:
        pass
    out, err = proc.communicate(timeout=120)
    return proc.returncode, out, err


def _trace(out_dir):
    path = os.path.join(out_dir, "service_trace.jsonl")
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# -- pool lifecycle ------------------------------------------------------------


def test_pool_spawn_ready_shutdown_zero_orphans(tmp_path):
    """Spawn → ready → drain-ordered shutdown: every worker is its own
    process group (never the server's), a clean shutdown needs zero
    kills, and the /proc scan over every group the pool ever spawned
    finds ZERO survivors."""
    pool = WorkerPool(2, str(tmp_path))
    pool.start()
    try:
        ready = set()
        deadline = time.monotonic() + 60
        while len(ready) < 2 and time.monotonic() < deadline:
            for wid, ev in pool.poll(1.0):
                if ev.get("ev") == "ready":
                    ready.add(wid)
                    pool.workers[wid].state = "idle"
        assert ready == {"w0", "w1"}
        own = os.getpgid(0)
        assert all(h.pgid != own for h in pool.workers.values())
        snap = pool.snapshot()
        assert snap["size"] == 2 and snap["idle"] == 2 and snap["busy"] == 0
    finally:
        res = pool.shutdown()
    assert res["survivors"] == []
    assert res["kills"] == 0  # a ready worker exits on the shutdown frame
    assert pool.orphans() == []
    assert all(h.proc.poll() is not None for h in pool.workers.values())


# -- crash containment (the acceptance e2e) ------------------------------------


def test_sigkill_busy_worker_resume_content_identical(tmp_path):
    """SIGKILL a worker mid-cell: the SERVER never dies, the replacement
    worker executes ONLY the cells the dead worker had not journaled,
    and the client-visible reply is content-identical to the undisturbed
    run of the same request on the same server."""
    request = {"kind": "probe", "cells": [
        {"label": "c0", "op": "ok", "value": 0},
        {"label": "s", "op": "sleep", "sleep_s": 3.0, "value": 1},
        {"label": "c2", "op": "ok", "value": 2},
    ]}
    out, proc, client = _start(tmp_path, "sigkill", "--workers", "1")
    try:
        ref = client.submit(request, request_id="ref", timeout=120)
        assert ref.get("ok") and ref.get("status") == "done"

        victim = client.submit(request, request_id="victim", wait=False)
        pid = None
        deadline = time.monotonic() + 30
        while pid is None and time.monotonic() < deadline:
            st = client.status()
            by = (st.get("workers") or {}).get("by_worker") or {}
            for w in by.values():
                if w.get("state") == "busy" and w.get("cell") == "s":
                    pid = w["pid"]
            if pid is None:
                time.sleep(0.05)
        assert pid is not None, "worker never reached the sleep cell"
        os.kill(pid, signal.SIGKILL)

        recovered = client.wait_result(victim["id"], timeout=120)
        reply = recovered["reply"]
        st = client.status()
        workers = st.get("workers") or {}
    finally:
        rc, _, err = _finish(proc, client)
    assert rc == 0, err[-2000:]
    assert reply.get("ok")
    assert reply["cells"] == ref["cells"]  # content-identical
    summary = reply.get("summary") or {}
    # c0 was journaled before the kill: recovered, never re-run
    assert summary.get("resumed_skipped", 0) >= 1
    assert summary.get("executed", 9) <= len(request["cells"]) - 1
    assert workers.get("restarts", 0) >= 1
    # the trace attributes the crash and the replacement
    events = [r.get("event") for r in _trace(out) if r.get("t") == "worker"]
    assert "crash" in events and "replace" in events


# -- SIGALRM-free deadlines ----------------------------------------------------


def test_parent_enforced_deadline_reaps_hung_worker(tmp_path):
    """A worker hung far past its per-cell deadline is killed by the
    PARENT (group-kill ladder — no SIGALRM in either process), the retry
    on the replacement completes the request in bounded wall, and the
    server serves throughout."""
    sentinel = str(tmp_path / "hang.once")
    out, proc, client = _start(
        tmp_path, "deadline", "--workers", "1",
        "--cell-deadline", "0.5", "--attempts", "2",
    )
    try:
        t0 = time.monotonic()
        reply = client.submit({"kind": "probe", "cells": [
            {"label": "hang", "op": "sleep", "sleep_s": 600,
             "once": sentinel, "value": 3},
            {"label": "after", "op": "ok", "value": 4},
        ]}, request_id="hang", timeout=120)
        wall = time.monotonic() - t0
        alive = client.submit(
            {"kind": "probe", "cells": [{"label": "ok", "op": "ok"}]},
            timeout=60,
        )
        st = client.status()
        workers = st.get("workers") or {}
    finally:
        rc, _, err = _finish(proc, client)
    assert rc == 0, err[-2000:]
    assert reply.get("ok") and reply.get("status") == "done"
    cells = {c["label"]: c for c in reply["cells"]}
    # the retried attempt (once-sentinel present) completed the cell:
    # a 600s uninterruptible hang cost one bounded deadline budget
    assert cells["hang"]["result"]["value"] == 3
    assert not cells["hang"].get("quarantined")
    assert cells["after"]["result"]["value"] == 4
    assert wall < 60.0
    assert alive.get("ok")
    assert workers.get("kills", 0) >= 1
    assert workers.get("restarts", 0) >= 1
    events = [r.get("event") for r in _trace(out) if r.get("t") == "worker"]
    assert "kill" in events  # deadline kill, not crash


# -- warm-affinity routing -----------------------------------------------------


def test_warm_affinity_repeat_lands_on_warm_worker(tmp_path):
    """With two idle workers, a repeat of an already-served request body
    routes to the worker that served it (scheduler pass 1, per-worker
    warm sets) — the other worker serves nothing."""
    body = {"kind": "probe", "cells": [{"label": "a", "op": "ok", "value": 1}]}
    out, proc, client = _start(tmp_path, "warm", "--workers", "2")
    try:
        r1 = client.submit(dict(body), request_id="r1", timeout=60)
        r2 = client.submit(dict(body), request_id="r2", timeout=60)
        st = client.status()
        by = (st.get("workers") or {}).get("by_worker") or {}
    finally:
        rc, _, err = _finish(proc, client)
    assert rc == 0, err[-2000:]
    assert r1.get("ok") and r2.get("ok")
    assert sorted(w.get("served", 0) for w in by.values()) == [0, 2]
    fin = [r for r in _trace(out)
           if r.get("t") == "request" and r.get("event") == "finished"]
    assert len(fin) == 2
    assert fin[0]["worker"] == fin[1]["worker"]
    # probe requests compile nothing: the repeat classifies warm with a
    # zero compile delta measured INSIDE the worker process
    assert fin[1].get("warm") is True
    assert fin[1].get("compiles", 1) == 0


# -- workers=0 fallback --------------------------------------------------------


def test_workers_zero_matches_inprocess_path(tmp_path):
    """``--workers 0`` is the PR 17 in-process path: the same request
    yields an identical client-visible reply, and the status surface
    carries no ``workers`` block at all."""
    request = {"kind": "probe", "cells": [
        {"label": f"c{i}", "op": "ok", "value": i} for i in range(3)
    ]}
    out0, proc0, client0 = _start(tmp_path, "inproc")
    try:
        r0 = client0.submit(request, request_id="same", timeout=60)
        st0 = client0.status()
    finally:
        rc0, _, err0 = _finish(proc0, client0)
    out1, proc1, client1 = _start(tmp_path, "pooled", "--workers", "1")
    try:
        r1 = client1.submit(request, request_id="same", timeout=60)
    finally:
        rc1, _, err1 = _finish(proc1, client1)
    assert rc0 == 0, err0[-2000:]
    assert rc1 == 0, err1[-2000:]
    assert "workers" not in st0
    for key in ("ok", "status", "id", "cells", "summary"):
        assert r0.get(key) == r1.get(key), key


# -- the silent-deadline fix (sweeps/resilient.py satellite) -------------------


def test_deadline_unenforced_note_surfaces(tmp_path):
    """An alarm-mode per-cell deadline requested from a NON-main thread
    cannot be enforced by SIGALRM: the executor emits exactly one
    `deadline_unenforced` note (previously it silently ran unbounded),
    `sweep_status.py` surfaces the count on the family row, and
    ``deadline="external"`` suppresses the note (the parent owns it)."""
    import sweep_status
    from blades_tpu.sweeps.resilient import (
        ResilienceOptions,
        run_cells_resilient,
    )
    from blades_tpu.telemetry.timeline import SweepAccounting

    def run_in_thread(trace, **opt_kw):
        sw = SweepAccounting("certify", total=2, path=trace)
        box = {}

        def run():
            box["out"] = run_cells_resilient(
                [("c0", {}), ("c1", {})], lambda payload: {"ok": True},
                sweep=sw,
                options=ResilienceOptions(
                    attempts=1, cell_deadline_s=0.5, sleep=lambda s: None,
                    **opt_kw,
                ),
            )

        t = threading.Thread(target=run)
        t.start()
        t.join(60)
        sw.close()
        with open(trace) as fh:
            return box["out"], [json.loads(line) for line in fh]

    (results, _, report), records = run_in_thread(str(tmp_path / "a.jsonl"))
    assert results == [{"ok": True}, {"ok": True}]
    notes = [r for r in records if r.get("t") == "deadline_unenforced"]
    # once per execution, not per cell — a 100-cell sweep must not bury
    # the trail under identical notes
    assert len(notes) == 1
    assert notes[0]["reason"] == "non_main_thread"
    assert notes[0]["deadline_s"] == 0.5
    summary = sweep_status.summarize_sweeps(records)
    assert summary["sweeps"]["certify"]["deadline_unenforced"] == 1

    _, records_ext = run_in_thread(
        str(tmp_path / "b.jsonl"), deadline="external",
    )
    assert not [r for r in records_ext
                if r.get("t") == "deadline_unenforced"]
    summary_ext = sweep_status.summarize_sweeps(records_ext)
    assert "deadline_unenforced" not in summary_ext["sweeps"]["certify"]
