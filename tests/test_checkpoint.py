"""Checkpoint/resume tests (capability absent in the reference)."""

import jax
import numpy as np

from blades_tpu import Simulator
from blades_tpu.datasets import Synthetic
from blades_tpu.ops.pytree import ravel
from blades_tpu.utils.checkpoint import restore_state, save_state


def test_save_restore_roundtrip(tmp_path):
    tree = {
        "a": jax.numpy.arange(6.0).reshape(2, 3),
        "b": (jax.numpy.zeros(4), jax.numpy.asarray(3, jax.numpy.int32)),
    }
    p = str(tmp_path / "ck.npz")
    save_state(p, tree)
    like = jax.tree_util.tree_map(jax.numpy.zeros_like, tree)
    out = restore_state(p, like)
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert int(out["b"][1]) == 3


def test_simulator_resume_bit_exact(tmp_path):
    def make():
        ds = Synthetic(num_clients=4, train_size=200, test_size=40, cache=False)
        return Simulator(ds, log_path=str(tmp_path / "out"), seed=5)

    ck = str(tmp_path / "state.npz")
    # straight 4-round run
    sim_a = make()
    sim_a.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
              validate_interval=100)
    ref = np.asarray(ravel(sim_a.server.state.params))

    # 2 rounds + checkpoint, then resume 2 more
    sim_b = make()
    sim_b.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
              validate_interval=100, checkpoint_path=ck, checkpoint_interval=2)
    sim_c = make()
    sim_c.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
              validate_interval=100, checkpoint_path=ck, resume=True)
    out = np.asarray(ravel(sim_c.server.state.params))
    np.testing.assert_array_equal(ref, out)
