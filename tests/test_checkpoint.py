"""Checkpoint/resume tests (capability absent in the reference)."""

import os

import jax
import numpy as np
import pytest

from blades_tpu import Simulator
from blades_tpu.datasets import Synthetic
from blades_tpu.ops.pytree import ravel
from blades_tpu.utils.checkpoint import checkpoint_file, restore_state, save_state


def test_save_restore_roundtrip(tmp_path):
    tree = {
        "a": jax.numpy.arange(6.0).reshape(2, 3),
        "b": (jax.numpy.zeros(4), jax.numpy.asarray(3, jax.numpy.int32)),
    }
    p = str(tmp_path / "ck.npz")
    save_state(p, tree)
    like = jax.tree_util.tree_map(jax.numpy.zeros_like, tree)
    out = restore_state(p, like)
    np.testing.assert_array_equal(out["a"], tree["a"])
    assert int(out["b"][1]) == 3


def test_save_is_atomic_no_tmp_left_behind(tmp_path):
    """Saves go through ``<path>.tmp`` + ``os.replace``: after a successful
    save no temp file remains, and overwriting an existing checkpoint can
    never leave a torn archive at the final path (the replace is atomic)."""
    tree = {"a": jax.numpy.arange(4.0)}
    p = str(tmp_path / "ck.npz")
    save_state(p, tree)
    save_state(p, tree)  # overwrite path exercises replace-over-existing
    assert os.path.exists(p)
    assert not os.path.exists(p + ".tmp")
    out = restore_state(p, {"a": jax.numpy.zeros(4)})
    np.testing.assert_array_equal(out["a"], tree["a"])


def test_truncated_checkpoint_raises_clean_error(tmp_path):
    """A torn file (kill mid-copy, disk corruption) fails with a clean
    ValueError naming the checkpoint — not a zipfile traceback from deep
    inside numpy."""
    tree = {"a": jax.numpy.arange(64.0), "b": jax.numpy.zeros((8, 8))}
    p = str(tmp_path / "ck.npz")
    save_state(p, tree)
    raw = open(checkpoint_file(p), "rb").read()
    like = jax.tree_util.tree_map(jax.numpy.zeros_like, tree)
    for cut in (len(raw) // 2, 10):
        with open(checkpoint_file(p), "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(ValueError, match="corrupt or unreadable"):
            restore_state(p, like)


def test_restored_leaves_are_owned_copies_safe_to_donate(tmp_path):
    """Regression (found by the supervised-resume e2e): ``jnp.asarray`` on
    an npz-loaded array can ZERO-COPY alias the numpy buffer on the CPU
    backend; the round program donates its state input, so XLA reused the
    alias as output memory while numpy freed the real owner — resumed
    rounds flakily read heap garbage (NaN/1e38 params). restore_state must
    return jax-owned copies. This canary donates a restored leaf, thrashes
    the heap with the same-size allocations, and checks the values held."""
    import jax.numpy as jnp

    src = {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(50_000,)).astype(np.float32)
    )}
    p = str(tmp_path / "ck")
    save_state(p, src)
    restored = restore_state(p, src)
    donating = jax.jit(lambda x: x * 1.0, donate_argnums=0)
    out = donating(restored["w"])
    for _ in range(16):  # heap churn over any freed aliased pages
        np.full(50_000, np.nan, np.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(src["w"]))


def test_simulator_resume_bit_exact(tmp_path):
    def make():
        ds = Synthetic(num_clients=4, train_size=200, test_size=40, cache=False)
        return Simulator(ds, log_path=str(tmp_path / "out"), seed=5)

    ck = str(tmp_path / "state.npz")
    # straight 4-round run
    sim_a = make()
    sim_a.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
              validate_interval=100)
    ref = np.asarray(ravel(sim_a.server.state.params))

    # 2 rounds + checkpoint, then resume 2 more
    sim_b = make()
    sim_b.run("mlp", global_rounds=2, local_steps=1, train_batch_size=8,
              validate_interval=100, checkpoint_path=ck, checkpoint_interval=2)
    sim_c = make()
    sim_c.run("mlp", global_rounds=4, local_steps=1, train_batch_size=8,
              validate_interval=100, checkpoint_path=ck, resume=True)
    out = np.asarray(ravel(sim_c.server.state.params))
    np.testing.assert_array_equal(ref, out)


def test_block_boundary_resume_bit_exact(tmp_path):
    """Round-block scheduling (run(block_size=...)) checkpoints and
    autosaves only block-boundary states, so a kill at a block boundary +
    resume must land bit-exactly on the uninterrupted run — and the whole
    block world must match the per-round world bit-for-bit (blocks are a
    scheduling choice, not a numerical one)."""

    def make(tag):
        ds = Synthetic(num_clients=4, train_size=200, test_size=40, cache=False)
        return Simulator(ds, log_path=str(tmp_path / tag), seed=5)

    common = dict(local_steps=1, train_batch_size=8, validate_interval=100)

    # per-round ground truth, 6 rounds
    sim_seq = make("seq")
    sim_seq.run("mlp", global_rounds=6, **common)
    ref = np.asarray(ravel(sim_seq.server.state.params))

    # uninterrupted block run: 6 rounds in blocks of 4 + remainder 2
    sim_blk = make("blk")
    sim_blk.run("mlp", global_rounds=6, block_size=4, **common)
    np.testing.assert_array_equal(
        ref, np.asarray(ravel(sim_blk.server.state.params))
    )

    # "kill" after the first full block (checkpoint at round 4 = block
    # boundary), then a fresh process resumes the remaining rounds — still
    # under block scheduling; the resumed remainder re-aligns
    ck = str(tmp_path / "blk_ck.npz")
    sim_b = make("kill")
    sim_b.run("mlp", global_rounds=4, block_size=4, checkpoint_path=ck,
              checkpoint_interval=4, **common)
    assert int(sim_b.server.state.round_idx) == 4  # boundary-aligned state
    sim_c = make("resume")
    sim_c.run("mlp", global_rounds=6, block_size=4, checkpoint_path=ck,
              resume=True, **common)
    np.testing.assert_array_equal(
        ref, np.asarray(ravel(sim_c.server.state.params))
    )
