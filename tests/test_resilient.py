"""Fault-tolerant sweep execution (blades_tpu/sweeps/resilient.py +
journal.py): poison-cell quarantine with sibling salvage, per-cell
deadlines + bounded-backoff retry, journaled resume that executes only
the remaining cells, and the kill-mid-sweep saboteur — the robustness
substrate every long sweep (certify/chaos, ROADMAP item 2's sweep
server) runs on."""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.sweeps import SweepCell  # noqa: E402
from blades_tpu.sweeps.journal import KILL_AT_ENV, SweepJournal  # noqa: E402
from blades_tpu.sweeps.resilient import (  # noqa: E402
    DeadlineExceeded,
    ResilienceOptions,
    run_grouped_resilient,
    soft_deadline,
)
from blades_tpu.telemetry.schema import validate_trace  # noqa: E402
from blades_tpu.telemetry.timeline import SweepAccounting  # noqa: E402


class _Trials:
    """Shape-only stand-in so grouping works without building arrays."""

    ndim = 3
    shape = (1, 4, 2)
    dtype = "float32"


def _cells(n):
    return [SweepCell(f"c{i}", agg=None, trials=_Trials(), f=0)
            for i in range(n)]


def _opts(runner, **kw):
    kw.setdefault("attempts", 2)
    kw.setdefault("base_delay_s", 0.0)
    kw.setdefault("sleep", lambda s: None)
    return ResilienceOptions(runner=runner, **kw)


def _ok_result(c):
    return {"worst_dev": 1.0, "label": c.label}


# -- journal ------------------------------------------------------------------


def test_journal_roundtrip_and_fingerprint_guard(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = SweepJournal(path, fingerprint="fp1")
    j.record("a", {"x": 1.5}, wall_s=0.25)
    j.record_quarantine("b", "ValueError: boom", "ValueError",
                        batch="g1", attempts=2)
    j.close()

    # resume with the matching fingerprint recovers both kinds
    r = SweepJournal(path, fingerprint="fp1", resume=True)
    assert r.resumed
    assert r.results() == {"a": {"x": 1.5}}
    assert r.entry("a")["wall_s"] == 0.25
    assert r.has("a") and r.has("b") and not r.has("c")
    assert r.quarantined()["b"]["error_type"] == "ValueError"
    assert r.quarantined()["b"]["batch"] == "g1"
    r.close()

    # a different config fingerprint silently starts FRESH: merging
    # results across configurations would fabricate a matrix no single
    # run produced
    f = SweepJournal(path, fingerprint="fp2", resume=True)
    assert not f.resumed
    assert f.results() == {} and not f.has("a")
    f.close()


def test_journal_tolerates_torn_tail(tmp_path):
    """A SIGKILL mid-append leaves a torn last line; every completed
    entry before it must still recover."""
    path = str(tmp_path / "j.jsonl")
    j = SweepJournal(path, fingerprint="fp")
    j.record("a", {"x": 1})
    j.record("b", {"x": 2})
    j.close()
    with open(path, "a") as f:
        f.write('{"kind": "cell", "cell": "c", "result": {"x":')  # torn
    r = SweepJournal(path, fingerprint="fp", resume=True)
    assert r.resumed
    assert sorted(r.results()) == ["a", "b"]
    r.close()


def test_journal_saboteur_sigkills_once(tmp_path):
    """The kill-mid-sweep test hook: BLADES_SWEEP_KILL_AT=N SIGKILLs the
    process right after the N-th journaled cell — exactly once, gated by
    the sentinel, so the relaunch completes (no jax; subprocess because
    SIGKILL is SIGKILL)."""
    path = str(tmp_path / "j.jsonl")
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from blades_tpu.sweeps.journal import SweepJournal\n"
        "import os\n"
        "j = SweepJournal(%r, fingerprint='fp',\n"
        "                 resume=os.environ.get('BLADES_RESUME') == '1')\n"
        "for i in range(3):\n"
        "    lab = 'c%%d' %% i\n"
        "    if not j.has(lab):\n"
        "        j.record(lab, {'i': i})\n"
        "print('DONE', len(j))\n"
    ) % (REPO, path)
    env = dict(os.environ, **{KILL_AT_ENV: "2"})
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == -signal.SIGKILL, (p.stdout, p.stderr)
    assert os.path.exists(path + ".kill_fired")

    # relaunch under resume: recovers the 2 journaled cells, the sentinel
    # disarms the saboteur, the remaining cell lands
    env["BLADES_RESUME"] = "1"
    p2 = subprocess.run([sys.executable, "-c", code], env=env,
                        capture_output=True, text=True, timeout=60)
    assert p2.returncode == 0, (p2.stdout, p2.stderr)
    assert "DONE 3" in p2.stdout

    # a FRESH launch (no resume) clears journal + sentinel and re-arms
    env.pop("BLADES_RESUME")
    p3 = subprocess.run([sys.executable, "-c", code], env=env,
                        capture_output=True, text=True, timeout=60)
    assert p3.returncode == -signal.SIGKILL, (p3.stdout, p3.stderr)


# -- deadlines ----------------------------------------------------------------


def test_soft_deadline_trips_and_restores():
    t0 = time.time()
    with pytest.raises(DeadlineExceeded):
        with soft_deadline(0.05):
            time.sleep(5.0)
    assert time.time() - t0 < 2.0
    # timer cancelled + handler restored: nothing fires afterwards
    with soft_deadline(None) as armed:
        assert armed is False
    time.sleep(0.08)


# -- quarantine / retry / degrade ---------------------------------------------


def test_poison_cell_quarantined_siblings_salvaged(tmp_path):
    """The tentpole contract: one poison cell in a batched group is
    isolated by bisection and quarantined with an attributable error
    (type + message + group fingerprint) while every sibling's result
    lands."""
    cells = _cells(4)
    calls = []

    def runner(group, key):
        labels = [c.label for c in group]
        calls.append(labels)
        if "c2" in labels:
            raise ValueError("poison in " + ",".join(labels))
        return [_ok_result(c) for c in group]

    trace = str(tmp_path / "sweep_trace.jsonl")
    sw = SweepAccounting("certify", total=4, path=trace)
    journal = SweepJournal(str(tmp_path / "j.jsonl"), fingerprint="fp")
    results, walls, report = run_grouped_resilient(
        cells, sweep=sw, journal=journal, options=_opts(runner),
    )
    sw.close()
    journal.close()

    assert [r and r["label"] for r in results] == ["c0", "c1", None, "c3"]
    assert report.summary()["quarantined"] == ["c2"]
    assert report.executed == 3
    assert report.degraded_groups >= 1
    # the full group was retried before bisection (transient-flake budget)
    assert report.retried >= 1
    q = report.quarantined[0]
    assert q["error_type"] == "ValueError"
    assert "poison" in q["error"]
    assert q["batch"]  # the group's program fingerprint

    records = [json.loads(l) for l in open(trace) if l.strip()]
    quar = [r for r in records if r.get("t") == "quarantine"]
    assert len(quar) == 1 and quar[0]["cell"] == "c2"
    assert quar[0]["error_type"] == "ValueError"
    retries = [r for r in records if r.get("t") == "retry"]
    assert retries and all(r["sweep"] == "certify" for r in retries)
    # the driver trail marks the quarantined cell done-with-error, and
    # every record the resilient layer emitted is schema-valid
    done = [r for r in records if r.get("t") == "sweep" and r.get("i")]
    assert len(done) == 4
    assert [r for r in done if r.get("quarantined")][0]["cell"] == "c2"
    assert validate_trace(trace) == []


def test_deadline_trip_retries_then_degrades(tmp_path):
    """A per-cell deadline trip on the batched group is retried, then
    degrades through bisection to per-cell execution — cells salvaged,
    the trail showing the retry."""
    cells = _cells(4)
    calls = []

    def runner(group, key):
        calls.append(len(group))
        if len(group) > 1:
            time.sleep(0.5)  # overruns len(group) * 0.02 deadline
        return [_ok_result(c) for c in group]

    trace = str(tmp_path / "sweep_trace.jsonl")
    sw = SweepAccounting("certify", total=4, path=trace)
    results, walls, report = run_grouped_resilient(
        cells, sweep=sw, options=_opts(runner, cell_deadline_s=0.02),
    )
    sw.close()

    assert all(r is not None for r in results)
    assert report.quarantined == []
    assert report.degraded_groups >= 1
    assert report.retried >= 1
    assert 1 in calls  # degraded all the way to per-cell execution
    records = [json.loads(l) for l in open(trace) if l.strip()]
    retries = [r for r in records if r.get("t") == "retry"]
    assert any("DeadlineExceeded" in r.get("error", "") for r in retries)


def test_clean_run_matches_plain_run_grouped():
    """With nothing failing, the resilient executor runs the exact same
    grouped programs — bit-identical results to run_grouped."""
    import jax

    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.audit import QUICK_GRIDS, battery_ctx, synthetic_honest
    from blades_tpu.sweeps import run_grouped

    trials = synthetic_honest(jax.random.PRNGKey(0), 2, 6, 8)
    ctx = battery_ctx(None, 6, 8, key=jax.random.PRNGKey(3))
    cells = [
        SweepCell("m/f1", get_aggregator("median"), trials, 1, ctx),
        SweepCell("tm/f1", get_aggregator("trimmedmean", num_byzantine=1),
                  trials, 1, ctx),
        SweepCell("m/f2", get_aggregator("median"), trials, 2, ctx),
    ]
    plain, _ = run_grouped(cells, grids=QUICK_GRIDS, use_jit=True,
                           return_walls=True)
    resilient, _, report = run_grouped_resilient(
        cells, grids=QUICK_GRIDS, use_jit=True,
    )
    assert resilient == plain
    assert report.retried == 0 and report.quarantined == []


# -- resume -------------------------------------------------------------------


def test_resume_executes_only_remaining(tmp_path):
    """A journal holding a prefix of the cells pins the relaunch to the
    remainder: recovered results merge idempotently, executed count is
    exactly the missing cells."""
    cells = _cells(4)
    path = str(tmp_path / "j.jsonl")

    j = SweepJournal(path, fingerprint="fp")
    ran = []

    def runner(group, key):
        ran.extend(c.label for c in group)
        return [_ok_result(c) for c in group]

    full, _, _ = run_grouped_resilient(
        cells, journal=j, options=_opts(runner),
    )
    j.close()
    assert ran == ["c0", "c1", "c2", "c3"]

    # keep only the first 2 journaled cells (an interrupted run)
    lines = [l for l in open(path) if l.strip()]
    cut = [l for l in lines
           if json.loads(l).get("kind") != "cell"
           or json.loads(l)["cell"] in ("c0", "c1")]
    with open(path, "w") as f:
        f.writelines(cut)

    j2 = SweepJournal(path, fingerprint="fp", resume=True)
    ran2 = []

    def runner2(group, key):
        ran2.extend(c.label for c in group)
        return [_ok_result(c) for c in group]

    trace = str(tmp_path / "sweep_trace.jsonl")
    sw = SweepAccounting("certify", total=4, path=trace)
    sw.resume(2, journal=path)
    resumed, _, report = run_grouped_resilient(
        cells, sweep=sw, journal=j2, options=_opts(runner2),
    )
    sw.close()
    j2.close()

    assert sorted(ran2) == ["c2", "c3"]  # only the remaining cells
    assert resumed == full               # idempotent merge
    assert report.resumed_skipped == 2 and report.executed == 2
    records = [json.loads(l) for l in open(trace) if l.strip()]
    assert [r["skipped"] for r in records if r.get("t") == "resume"] == [2]
    re_emits = [r for r in records
                if r.get("t") == "sweep" and r.get("resumed")]
    assert {r["cell"] for r in re_emits} == {"c0", "c1"}
    assert validate_trace(trace) == []


def test_fully_complete_resume_executes_zero_cells(tmp_path):
    """The resume-overhead invariant perf_report gates: resuming a
    complete sweep executes nothing."""
    cells = _cells(3)
    path = str(tmp_path / "j.jsonl")
    j = SweepJournal(path, fingerprint="fp")
    _, _, _ = run_grouped_resilient(
        cells, journal=j,
        options=_opts(lambda g, k: [_ok_result(c) for c in g]),
    )
    j.close()

    j2 = SweepJournal(path, fingerprint="fp", resume=True)

    def never(group, key):
        raise AssertionError("a complete sweep must not execute cells")

    results, _, report = run_grouped_resilient(
        cells, journal=j2, options=_opts(never),
    )
    j2.close()
    assert all(r is not None for r in results)
    assert report.executed == 0 and report.resumed_skipped == 3


def test_certify_matrix_resume_merges_identical(tmp_path):
    """Driver-level resume: an interrupted certify journal (prefix of the
    cells) resumes into a matrix content-identical (timing stripped) to
    the uninterrupted run's."""
    import certify

    def mkargs():
        return argparse.Namespace(
            clients=4, dim=4, trials=1, seed=0, c=None,
            aggs=["mean", "median"], quick=True, no_async=True,
            tau_max=2, no_jit=False, sequential=False,
            out=str(tmp_path),
        )

    path = str(tmp_path / "j.jsonl")
    j = SweepJournal(path, fingerprint="fp")
    ref = certify.certify_matrix(mkargs(), journal=j)
    j.close()
    assert ref["ok"] and ref["resumed_skipped"] == 0

    # drop the journal's tail: the last 2 cells become "not yet run"
    lines = [l for l in open(path) if l.strip()]
    cell_lines = [l for l in lines if json.loads(l).get("kind") == "cell"]
    drop = {json.loads(l)["cell"] for l in cell_lines[-2:]}
    with open(path, "w") as f:
        f.writelines(
            l for l in lines
            if json.loads(l).get("kind") != "cell"
            or json.loads(l)["cell"] not in drop
        )

    j2 = SweepJournal(path, fingerprint="fp", resume=True)
    res = certify.certify_matrix(mkargs(), journal=j2)
    j2.close()
    assert res["resumed_skipped"] == len(cell_lines) - 2

    def strip(m):
        m = json.loads(json.dumps(m))
        for k in ("resumed_skipped", "retried", "degraded_groups"):
            m.pop(k)
        for row in m["cells"] + m["async_cells"]:
            row.pop("search_s")
        return m

    assert strip(ref) == strip(res)


def test_certify_sequential_quarantine_records(tmp_path, monkeypatch):
    """The sequential (--sequential) certify path routes through the same
    per-cell resilient loop: a poison cell is retried, quarantined with
    the full record trail (quarantine event + flagged sweep record +
    journal entry), and every other cell's result lands."""
    import blades_tpu.audit as audit_mod
    import certify

    real = audit_mod.search_cell

    def poison(agg, trials, f, **kw):
        if kw.get("cell_label") == "median/f1":
            raise ValueError("sequential poison")
        return real(agg, trials, f, **kw)

    monkeypatch.setattr(audit_mod, "search_cell", poison)

    args = argparse.Namespace(
        clients=4, dim=4, trials=1, seed=0, c=None,
        aggs=["mean", "median"], quick=True, no_async=True,
        tau_max=2, no_jit=False, sequential=True, out=str(tmp_path),
        attempts=2,
    )
    trace = str(tmp_path / "sweep_trace.jsonl")
    sw = SweepAccounting("certify", total=6, path=trace)
    journal = SweepJournal(str(tmp_path / "j.jsonl"), fingerprint="fp")
    from blades_tpu.sweeps.resilient import ResilienceOptions

    m = certify.certify_matrix(
        args, sweep=sw, journal=journal,
        resilience=ResilienceOptions(attempts=2, base_delay_s=0.0,
                                     sleep=lambda s: None),
    )
    sw.close()
    journal.close()

    assert m["ok"] is False
    assert [q["cell"] for q in m["quarantined_cells"]] == ["median/f1"]
    assert m["quarantined_cells"][0]["error_type"] == "ValueError"
    assert len(m["cells"]) == 3  # mean/f0 mean/f1 median/f0 survived
    assert journal.has("median/f1")  # a resume will not replay the poison

    records = [json.loads(l) for l in open(trace) if l.strip()]
    quar = [r for r in records if r.get("t") == "quarantine"]
    assert len(quar) == 1 and quar[0]["cell"] == "median/f1"
    assert [r for r in records if r.get("t") == "retry"]
    flagged = [r for r in records
               if r.get("t") == "sweep" and r.get("quarantined")]
    assert len(flagged) == 1 and flagged[0]["cell"] == "median/f1"
    assert validate_trace(trace) == []


# -- status surfaces ----------------------------------------------------------


def test_sweep_status_reports_resilience_counts():
    from sweep_status import summarize_sweeps

    records = [
        {"t": "sweep", "sweep": "certify", "cell": "a", "wall_s": 1.0,
         "ts": 100.0, "i": 1, "total": 3},
        {"t": "resume", "sweep": "certify", "skipped": 1, "total": 3},
        {"t": "sweep", "sweep": "certify", "cell": "a", "wall_s": 0.0,
         "ts": 101.0, "i": 1, "total": 3, "resumed": True},
        {"t": "retry", "what": "sweep_group", "attempt": 1, "delay_s": 0.5,
         "sweep": "certify", "batch": "g"},
        {"t": "sweep", "sweep": "certify", "cell": "b", "wall_s": 1.0,
         "ts": 102.0, "i": 2, "total": 3, "retries": 1},
        {"t": "quarantine", "sweep": "certify", "cell": "c",
         "error": "ValueError: boom", "error_type": "ValueError"},
        {"t": "sweep", "sweep": "certify", "cell": "c", "wall_s": 0.0,
         "ts": 103.0, "i": 3, "total": 3, "ok": False,
         "error": "ValueError: boom", "error_type": "ValueError",
         "quarantined": True},
    ]
    fam = summarize_sweeps(records)["sweeps"]["certify"]
    assert fam["retried"] == 1
    assert fam["quarantined"] == 1
    assert fam["resumed_skipped"] == 1
    assert fam["errors"] == 1
    # progress dedupes the resumed re-emit: 3 of 3, not 4 of 3
    assert fam["done"] == 3 and fam["frac"] == 1.0


def test_runs_sweep_progress_reports_resilience(tmp_path):
    from runs import sweep_progress

    trace = str(tmp_path / "sweep_trace.jsonl")
    now = time.time()
    records = [
        {"t": "resume", "sweep": "certify", "skipped": 2, "total": 4},
        {"t": "sweep", "sweep": "certify", "cell": "a", "wall_s": 0.0,
         "ts": now, "i": 1, "total": 4, "resumed": True},
        {"t": "sweep", "sweep": "certify", "cell": "b", "wall_s": 0.0,
         "ts": now, "i": 2, "total": 4, "resumed": True},
        {"t": "retry", "what": "sweep_cell", "attempt": 1, "delay_s": 0.5,
         "sweep": "certify", "cell": "c"},
        {"t": "sweep", "sweep": "certify", "cell": "c", "wall_s": 1.0,
         "ts": now, "i": 3, "total": 4, "retries": 1},
        {"t": "quarantine", "sweep": "certify", "cell": "d",
         "error": "TypeError: nope", "error_type": "TypeError"},
        {"t": "sweep", "sweep": "certify", "cell": "d", "wall_s": 0.0,
         "ts": now, "i": 4, "total": 4, "ok": False,
         "error": "TypeError: nope", "error_type": "TypeError",
         "quarantined": True},
    ]
    with open(trace, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    out = sweep_progress([{"artifacts": [trace]}], repo=str(tmp_path))
    assert out["cells_completed"] == 4
    assert out["retried"] == 1
    assert out["quarantined"] == 1
    assert out["resumed_skipped"] == 2
    assert out["resumes"] == 1


# -- kill-mid-sweep, tier-1 reduced form --------------------------------------


CHAOS = os.path.join(REPO, "scripts", "chaos.py")


def test_chaos_kill_mid_sweep_resume_tier1(tmp_path):
    """The chaos sweep's saboteur path, tier-1 reduced: SIGKILL after the
    first journaled seed, relaunch under BLADES_RESUME=1 recovers that
    seed's result and executes only the remaining one — the sweep
    completes with zero violations and a complete result set. (The
    resumed-equals-uninterrupted content identity is pinned at the
    certify driver by test_certify_matrix_resume_merges_identical and
    the slow supervised e2e; this test spends its two subprocesses on
    the SIGKILL itself.)"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("BLADES_RESUME", None)
    env.pop(KILL_AT_ENV, None)

    out = tmp_path / "sup"
    killed = subprocess.run(
        [sys.executable, CHAOS, "--sweep", "2", "--out", str(out)],
        capture_output=True, text=True, cwd=REPO,
        env=dict(env, **{KILL_AT_ENV: "1"}), timeout=420,
    )
    assert killed.returncode == -signal.SIGKILL, (
        killed.stdout, killed.stderr,
    )
    journal = [json.loads(l)
               for l in open(out / "sweep_journal.jsonl") if l.strip()]
    assert sum(r.get("kind") == "cell" for r in journal) == 1

    resumed = subprocess.run(
        [sys.executable, CHAOS, "--sweep", "2", "--out", str(out)],
        capture_output=True, text=True, cwd=REPO,
        env=dict(env, BLADES_RESUME="1"), timeout=420,
    )
    assert resumed.returncode == 0, (resumed.stdout, resumed.stderr)
    res = json.loads(resumed.stdout.splitlines()[-1])
    assert res["ok"] is True
    assert res["resumed"] is True
    assert res["resumed_skipped"] == 1
    assert res["scenarios"] == 2 and len(res["results"]) == 2
    assert res["violations"] == [] and res["quarantined_cells"] == []
    # seed 0's row came from the journal, seed 1's from execution — the
    # merged result set is seed-ordered and complete
    assert [r["seed"] for r in res["results"]] == [0, 1]

    # the trace pins it: one resume record, and exactly one executed
    # (non-resumed) driver cell after it
    trace = out / "sweep_trace.jsonl"
    records = [json.loads(l) for l in open(trace) if l.strip()]
    r_idx = max(i for i, r in enumerate(records) if r.get("t") == "resume")
    executed = [r for r in records[r_idx:]
                if r.get("t") == "sweep" and r.get("i")
                and not r.get("resumed")]
    assert len(executed) == 1
    assert validate_trace(str(trace)) == []


# -- the slow e2e: supervised certify SIGKILL ---------------------------------


@pytest.mark.slow
def test_certify_sigkill_resume_supervised_e2e(tmp_path):
    """The acceptance e2e: certify.py SIGKILLed mid-sweep under the
    supervisor resumes with BLADES_RESUME=1 (the supervisor's relaunch
    contract), executes only the remaining cells, and produces a
    cert_matrix.json content-identical (timing fields aside) to an
    uninterrupted run."""
    from blades_tpu.supervision import Supervisor

    CERTIFY = os.path.join(REPO, "scripts", "certify.py")
    argv = ["--clients", "6", "--dim", "8", "--trials", "2", "--quick",
            "--no-async", "--aggs", "mean", "median", "krum"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("BLADES_RESUME", None)
    env.pop(KILL_AT_ENV, None)

    ref_out = tmp_path / "ref"
    p = subprocess.run(
        [sys.executable, CERTIFY, *argv, "--out", str(ref_out)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600,
    )
    assert p.returncode == 0, (p.stdout, p.stderr)
    ref = json.load(open(ref_out / "cert_matrix.json"))

    sup_out = tmp_path / "sup"
    telem = str(tmp_path / "sup_telemetry.jsonl")
    result = Supervisor(
        [sys.executable, CERTIFY, *argv, "--out", str(sup_out)],
        attempts=2, base_delay_s=0.1, poll_s=0.2, telemetry_path=telem,
        heartbeat_file=str(tmp_path / "hb"),
        env={"JAX_PLATFORMS": "cpu", KILL_AT_ENV: "4"},
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    ).run()
    assert result.ok
    assert result.attempts[0].returncode == -signal.SIGKILL
    assert result.attempts[1].resumed
    res = json.load(open(sup_out / "cert_matrix.json"))
    assert res["resumed"] is True
    assert res["resumed_skipped"] >= 4

    def strip(m):
        m = json.loads(json.dumps(m))
        for k in ("wall_s", "resumed", "resumed_skipped", "retried",
                  "degraded_groups"):
            m.pop(k, None)
        for row in m["cells"] + m["async_cells"]:
            row.pop("search_s")
        return m

    assert strip(ref) == strip(res)

    # pinned via sweep records: the resumed attempt executed only the
    # remaining cells
    records = [json.loads(l)
               for l in open(sup_out / "sweep_trace.jsonl") if l.strip()]
    r_idx = max(i for i, r in enumerate(records) if r.get("t") == "resume")
    skipped = records[r_idx]["skipped"]
    total = records[r_idx]["total"]
    executed = [r for r in records[r_idx:]
                if r.get("t") == "sweep" and r.get("i")
                and not r.get("resumed")]
    assert len(executed) == total - skipped
