"""LEAF utilities tests (pure python; no jax needed)."""

import json

import pytest

from blades_tpu.leaf import iid_divide
from blades_tpu.leaf.remove_users import remove_small_users
from blades_tpu.leaf.sample import sample_leaf
from blades_tpu.leaf.split_data import split_leaf
from blades_tpu.leaf.stats import leaf_stats
from blades_tpu.leaf.util import read_leaf_dir, write_leaf_json


@pytest.fixture
def leaf_data(tmp_path):
    data = {
        "users": [f"u{i}" for i in range(5)],
        "num_samples": [4, 8, 12, 16, 20],
        "user_data": {
            f"u{i}": {
                "x": [[float(i), float(j)] for j in range(4 * (i + 1))],
                "y": [j % 2 for j in range(4 * (i + 1))],
            }
            for i in range(5)
        },
    }
    write_leaf_json(data, str(tmp_path / "all.json"))
    return data, tmp_path


def test_iid_divide_even_and_ragged():
    assert iid_divide(list(range(10)), 2) == [list(range(5)), list(range(5, 10))]
    groups = iid_divide(list(range(11)), 3)
    assert sorted(sum(groups, [])) == list(range(11))
    assert {len(g) for g in groups} <= {3, 4}


def test_read_write_roundtrip(leaf_data):
    data, tmp = leaf_data
    loaded = read_leaf_dir(str(tmp))
    assert loaded["users"] == data["users"]
    assert sum(loaded["num_samples"]) == 60


def test_sample_noniid_budget(leaf_data):
    data, _ = leaf_data
    out = sample_leaf(data, fraction=0.5, iid=False, seed=1)
    assert sum(out["num_samples"]) >= 0.5 * 60
    for u in out["users"]:
        assert out["user_data"][u] == data["user_data"][u]


def test_sample_iid_pools(leaf_data):
    data, _ = leaf_data
    out = sample_leaf(data, fraction=0.5, iid=True, iid_user_frac=0.5, seed=1)
    assert sum(out["num_samples"]) == 30
    assert len(out["users"]) == 2


def test_split_preserves_samples(leaf_data):
    data, _ = leaf_data
    train, test = split_leaf(data, frac=0.75, seed=0)
    assert sum(train["num_samples"]) + sum(test["num_samples"]) == 60
    assert sum(train["num_samples"]) >= 0.7 * 60


def test_remove_small_users(leaf_data):
    data, _ = leaf_data
    out = remove_small_users(data, min_samples=10)
    assert out["users"] == ["u2", "u3", "u4"]


def test_stats(leaf_data):
    data, _ = leaf_data
    s = leaf_stats(data)
    assert s["num_users"] == 5
    assert s["num_samples"] == 60
    assert s["min"] == 4 and s["max"] == 20


def test_split_by_user_holds_out_users(leaf_data):
    from blades_tpu.leaf.split_data import split_leaf_by_user

    data, _ = leaf_data
    train, test = split_leaf_by_user(data, frac=0.6, seed=0)
    assert len(train["users"]) == 3 and len(test["users"]) == 2
    assert not set(train["users"]) & set(test["users"])  # user-disjoint
    assert sum(train["num_samples"]) + sum(test["num_samples"]) == 60
    for side in (train, test):  # samples travel whole with their user
        for u in side["users"]:
            assert side["user_data"][u] == data["user_data"][u]


def test_preprocess_pipeline_and_verify(leaf_data, tmp_path, capsys):
    from blades_tpu.leaf.preprocess import preprocess, verify

    data, src = leaf_data
    out = tmp_path / "out"
    stats = preprocess(
        str(src), str(out), sample="niid", sample_frac=0.5,
        min_samples=5, train="sample", train_frac=0.8,
        sample_seed=1, split_seed=2,
    )
    assert (out / "sampled_data" / "sampled.json").exists()
    assert (out / "rem_user_data" / "pruned.json").exists()
    assert (out / "train" / "train.json").exists()
    assert (out / "test" / "test.json").exists()
    manifest = out / "meta" / "manifest.json"
    assert manifest.exists()
    assert stats["num_users"] >= 1

    assert verify(str(out), str(manifest)) is True
    # corrupt one stage output: verify must fail
    (out / "train" / "train.json").write_text('{"users": []}')
    assert verify(str(out), str(manifest)) is False

    # stage-skip idempotency: rerun leaves existing stages untouched
    preprocess(str(src), str(out), sample="niid", sample_frac=0.5,
               min_samples=5, train="sample")
    assert "already been generated" in capsys.readouterr().out


def test_download_offline_gate(tmp_path, monkeypatch):
    """The GDrive fetcher must refuse (not hang) when offline, and use an
    already-present archive without any network touch."""
    import zipfile

    from blades_tpu.leaf.download import (
        download_and_extract,
        download_file_from_google_drive,
    )

    monkeypatch.setenv("BLADES_TPU_OFFLINE", "1")
    with pytest.raises(RuntimeError, match="BLADES_TPU_OFFLINE"):
        download_file_from_google_drive("fakeid", str(tmp_path / "x.zip"))

    archive = tmp_path / "dataset.zip"
    with zipfile.ZipFile(archive, "w") as z:
        z.writestr("all_data/data.json", '{"users": []}')
    out = download_and_extract("fakeid", str(tmp_path))
    assert (tmp_path / "all_data" / "data.json").exists()
    assert not archive.exists()  # archive removed after extraction
    assert out == str(tmp_path)


def test_drive_confirm_form_parsing():
    from blades_tpu.leaf.download import _parse_confirm_form

    html = '''<html><body>
    <form id="download-form" action="https://drive.usercontent.google.com/download" method="get">
    <input type="hidden" name="id" value="FILEID">
    <input type="hidden" name="confirm" value="t">
    <input type="hidden" name="uuid" value="abc-123">
    </form></body></html>'''
    action, params = _parse_confirm_form(html)
    assert action == "https://drive.usercontent.google.com/download"
    assert params == {"id": "FILEID", "confirm": "t", "uuid": "abc-123"}
    assert _parse_confirm_form("<html>no form here</html>") is None


def test_fetch_to_offline_and_cleanup(tmp_path, monkeypatch):
    import io

    from blades_tpu.utils.fetch import fetch_to

    dest = str(tmp_path / "f.bin")
    monkeypatch.setenv("BLADES_TPU_OFFLINE", "1")
    with pytest.raises(RuntimeError, match="BLADES_TPU_OFFLINE"):
        fetch_to(dest, lambda: io.BytesIO(b"x"), "thing")

    monkeypatch.delenv("BLADES_TPU_OFFLINE")
    assert fetch_to(dest, lambda: io.BytesIO(b"payload"), "thing") == dest
    assert open(dest, "rb").read() == b"payload"

    class Boom(io.RawIOBase):
        def read(self, n=-1):
            raise OSError("network died")

    with pytest.raises(RuntimeError, match="network died"):
        fetch_to(str(tmp_path / "g.bin"), lambda: Boom(), "thing")
    assert not (tmp_path / "g.bin.part").exists()  # tmp cleaned up
