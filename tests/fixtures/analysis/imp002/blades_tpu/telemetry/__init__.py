"""Fixture: IMP002. Reference counterpart: none — lint fixture."""
from blades_tpu.telemetry import metric_pack  # VIOLATION: submodule-only
