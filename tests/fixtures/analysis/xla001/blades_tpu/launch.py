"""Fixture: XLA001. Reference counterpart: none — lint fixture."""

CHILD_ENV = {"XLA_FLAGS": "--xla_fixture_unprobed_flag=1"}  # VIOLATION
