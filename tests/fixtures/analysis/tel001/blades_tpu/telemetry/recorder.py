"""Fixture: TEL001. Reference counterpart: none — lint fixture."""
import json


class Recorder:
    def _emit(self, record):
        self._fh.write(json.dumps(record) + "\n")  # VIOLATION: per-span I/O

    def flush(self):
        pass
