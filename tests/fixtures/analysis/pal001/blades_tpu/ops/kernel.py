"""Fixture: PAL001. Reference counterpart: none — lint fixture."""
import functools

import jax
from jax import lax
from jax.experimental import pallas as pl


def _kernel(u_ref, o_ref, *, k):
    def body(i, acc):
        return acc + u_ref[i, :]

    # VIOLATION: in-kernel loop construct (Mosaic proxy rejects it)
    o_ref[...] = lax.fori_loop(0, k, body, u_ref[0, :] * 0.0)


def _fixture_pallas_ok(k, d):
    try:
        _run.lower(jax.ShapeDtypeStruct((k, d), "float32")).compile()
        return True
    except Exception:
        return False


@jax.jit
def _run(u):
    return pl.pallas_call(functools.partial(_kernel, k=4), grid=(1,))(u)


def column_sum(u):
    if _fixture_pallas_ok(*u.shape):
        return _run(u)
    return u.sum(axis=0)
