"""Fixture: ALIAS001. Reference counterpart: none — lint fixture."""
import numpy as np
import jax.numpy as jnp


def restore(path, n):
    z = np.load(path)
    return [jnp.asarray(z[f"leaf_{i}"]) for i in range(n)]  # VIOLATION
