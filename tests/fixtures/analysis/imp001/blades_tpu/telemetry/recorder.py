"""Fixture: IMP001. Reference counterpart: none — lint fixture."""
import json
import jax  # VIOLATION: module-scope jax in a pre-jax-contracted file


class Recorder:
    def snapshot(self):
        return json.dumps({"backend": jax.default_backend()})
