"""Fixture: SCHEMA001. Reference counterpart: none — lint fixture."""
from blades_tpu.telemetry import get_recorder


def log_surprise():
    get_recorder().event("fixture_undeclared_type", x=1)  # VIOLATION
