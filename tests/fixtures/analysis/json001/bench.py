"""Fixture: JSON001 — a gate script whose main() lacks the catch-all."""
import json


def main():
    # VIOLATION: no top-level try/except funneling failures to one line
    print(json.dumps({"metric": "fixture", "value": 1}))


if __name__ == "__main__":
    main()
