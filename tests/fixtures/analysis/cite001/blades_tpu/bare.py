x = 1  # VIOLATION: no module docstring / citation
