"""Fixture: SYNC001. Reference counterpart: none — lint fixture."""
import jax.numpy as jnp


def aggregate(updates, state=(), **ctx):
    norm = jnp.linalg.norm(updates, axis=1)
    worst = norm.max().item()  # VIOLATION: host sync in a traced body
    return updates.mean(axis=0) / worst, state
