"""Multi-tenant scheduler + deadline-aware admission
(`blades_tpu/service/scheduler.py`): priority classes, weighted fair
share, per-tenant quota attribution, preemption requeue semantics, warm
affinity, and the CostEstimator's failure modes (cold start admits,
every denominator guarded).

Pure-unit: dict fixtures and an injected clock — no server, no jax
(the module is IMP001-contracted; the subprocess import probe lives in
tests/test_analysis.py, the e2e scenarios in tests/test_service.py and
the chaos drills).

Reference counterpart: none — the reference has no serving surface
(`src/blades/simulator.py`).
"""

import pytest

from blades_tpu.service.scheduler import (
    PRIORITIES,
    CostEstimator,
    ScheduledRequest,
    TenantScheduler,
    priority_rank,
)


def _req(rid, tenant="t", priority="normal", affinity=None, est_s=None):
    return ScheduledRequest(
        request_id=rid, request={}, tenant=tenant, priority=priority,
        affinity=affinity, est_s=est_s,
    )


def _drain(sched, charge_s=1.0):
    """Pick-and-charge until empty; returns the request ids in served
    order (each slice charged equally so fairness, not luck, orders)."""
    order = []
    while not sched.empty():
        e = sched.pick(timeout=0)
        order.append(e.request_id)
        sched.charge(e.tenant, charge_s)
        sched.done(e)
    return order


# -- priority classes ----------------------------------------------------------


def test_priority_rank_and_unknown_rejected():
    assert [priority_rank(p) for p in PRIORITIES] == [0, 1, 2]
    assert PRIORITIES == ("interactive", "normal", "batch")
    with pytest.raises(ValueError):
        priority_rank("urgent")


def test_priority_classes_schedule_strictly_first():
    s = TenantScheduler(max_queue=8)
    s.put(_req("b", priority="batch"))
    s.put(_req("n", priority="normal"))
    s.put(_req("i", priority="interactive"))
    assert _drain(s) == ["i", "n", "b"]


def test_waiting_above_is_the_preemption_signal():
    s = TenantScheduler(max_queue=8)
    assert not s.waiting_above("batch")
    s.put(_req("n", priority="normal"))
    assert s.waiting_above("batch")
    assert not s.waiting_above("normal")
    assert not s.waiting_above("interactive")


# -- weighted fair share -------------------------------------------------------


def test_fair_share_flood_does_not_starve_victim():
    """A tenant submitting 4 requests and a tenant submitting 2 must
    alternate — FIFO would serve the flood 4:0 first."""
    s = TenantScheduler(max_queue=16)
    for i in range(4):
        s.put(_req(f"f{i}", tenant="flood"))
    s.put(_req("v0", tenant="victim"))
    s.put(_req("v1", tenant="victim"))
    order = _drain(s)
    # both victim requests served within the first four slots
    assert set(order[:4]) >= {"v0", "v1"}
    # and within a tenant, FIFO order holds
    assert order.index("f0") < order.index("f1") < order.index("f2")


def test_weights_double_share():
    """weight=2 accrues virtual time half as fast: under equal charge
    the heavy tenant is served two slices for the light tenant's one."""
    s = TenantScheduler(max_queue=16, weights={"heavy": 2.0})
    for i in range(4):
        s.put(_req(f"h{i}", tenant="heavy"))
        s.put(_req(f"l{i}", tenant="light"))
    order = _drain(s)
    assert order == ["h0", "l0", "h1", "l1", "h2", "h3", "l2", "l3"]
    # the contended window serves heavy 2:1
    assert sum(1 for r in order[:6] if r.startswith("h")) == 4


def test_idle_tenant_cannot_bank_fairness_credit():
    """A tenant waking from idle starts at the active floor — it must
    alternate with the long-running tenant, not monopolize the worker to
    'catch up' on credit it banked while absent."""
    s = TenantScheduler(max_queue=16)
    s.put(_req("a0", tenant="a"))
    s.put(_req("a1", tenant="a"))
    s.charge("a", 100.0)  # a has been running a long time
    s.put(_req("b0", tenant="b"))
    s.put(_req("b1", tenant="b"))
    assert _drain(s) == ["a0", "b0", "a1", "b1"]


# -- quotas & overflow attribution ---------------------------------------------


def test_tenant_quota_overflow_blames_the_flooder():
    s = TenantScheduler(max_queue=8, tenant_quota=2)
    s.put(_req("f0", tenant="flood"))
    s.put(_req("f1", tenant="flood"))
    verdict = s.overflow("flood")
    assert verdict == {
        "reason": "backpressure", "scope": "tenant", "tenant": "flood",
        "tenant_depth": 2, "tenant_quota": 2,
    }
    # the victim's quota is untouched by the flood
    assert s.overflow("victim") is None


def test_global_overflow_blames_the_deepest_tenant():
    s = TenantScheduler(max_queue=3)  # no per-tenant quota
    s.put(_req("f0", tenant="flood"))
    s.put(_req("f1", tenant="flood"))
    s.put(_req("v0", tenant="victim"))
    verdict = s.overflow("victim")
    assert verdict["scope"] == "global"
    assert verdict["tenant"] == "flood"  # deepest queue, not the asker
    assert verdict["tenant_depth"] == 2
    assert verdict["queue_depth"] == 3 and verdict["max_queue"] == 3


# -- preemption requeue --------------------------------------------------------


def test_requeue_keeps_seq_and_counts_preemptions():
    """A preempted request re-enters at the head of its tenant's line
    (original seq), with only the preemption counter advanced."""
    s = TenantScheduler(max_queue=8)
    s.put(_req("long", tenant="t", priority="batch"))
    entry = s.pick(timeout=0)
    s.put(_req("later", tenant="t", priority="batch"))
    seq = entry.seq
    s.requeue(entry)
    assert entry.preemptions == 1
    nxt = s.pick(timeout=0)
    assert nxt.request_id == "long" and nxt.seq == seq
    s.requeue(nxt)
    assert nxt.preemptions == 2


# -- warm affinity -------------------------------------------------------------


def test_warm_first_within_tenant():
    s = TenantScheduler(max_queue=8)
    assert not s.is_warm("fp-warm")
    s.note_warm("fp-warm")
    s.note_warm(None)  # no-op, never raises
    assert s.is_warm("fp-warm") and not s.is_warm(None)
    s.put(_req("cold", affinity="fp-cold"))
    s.put(_req("warm", affinity="fp-warm"))
    assert _drain(s) == ["warm", "cold"]  # despite cold's earlier seq


# -- introspection -------------------------------------------------------------


def test_depth_by_class_composition_and_backlog():
    clock = [100.0]
    s = TenantScheduler(max_queue=8, clock=lambda: clock[0])
    s.put(_req("i0", tenant="alice", priority="interactive", est_s=2.0))
    clock[0] = 103.0
    s.put(_req("b0", tenant="miner", priority="batch", est_s=5.0))
    s.put(_req("b1", tenant="miner", priority="batch"))  # no estimate
    assert s.depth_by_class() == {
        "interactive": 1, "normal": 0, "batch": 2,
    }
    clock[0] = 105.0
    comp = s.composition()
    assert comp["alice"] == {
        "depth": 1, "oldest_age_s": 5.0, "priority": "interactive",
    }
    assert comp["miner"]["depth"] == 2
    assert comp["miner"]["priority"] == "batch"
    # backlog at `normal` sees only work at-or-above normal; unestimated
    # entries contribute zero (advisory-optimistic)
    assert s.backlog_s("normal") == 2.0
    assert s.backlog_s("batch") == 7.0
    # the in-flight request's estimate counts toward every backlog
    e = s.pick(timeout=0)
    assert e.request_id == "i0"
    assert s.backlog_s("normal") == 2.0
    assert s.backlog_s("batch") == 7.0
    s.done(e)
    assert s.backlog_s("batch") == 5.0
    # an idle scheduler reports clean surfaces
    assert s.pick(timeout=0).request_id in {"b0", "b1"}
    assert s.composition().keys() == {"miner"}


# -- CostEstimator -------------------------------------------------------------


def test_estimator_cold_start_has_no_estimate_and_admits():
    """A fresh server (empty snapshot, empty cache) must produce NO
    estimate — and therefore admit — without ever dividing by zero."""
    est = CostEstimator(lambda: None, lambda: None)
    assert est.estimate(100) is None
    assert est.cold_build_s() == 0.0
    assert est.verdict(100, 1e-9) == ("no_estimate", None)
    assert est.verdict(100, None) == ("ok", None)
    # zeroed history (counters exist, nothing done) is still cold start
    est = CostEstimator(
        lambda: {"cells": {"done": 0}, "split": {},
                 "requests": {"cold": 0}},
        lambda: {"by_key": {}},
    )
    assert est.estimate(5) is None
    assert est.verdict(5, 0.001) == ("no_estimate", None)
    assert est.cold_build_s() == 0.0
    # degenerate request shapes never estimate either
    assert est.estimate(0) is None


def test_estimator_warm_cold_and_verdicts():
    snap = {
        "cells": {"done": 10},
        "split": {"execute_s": 5.0, "build_s": 6.0},
        "requests": {"cold": 2},
    }
    cache = {"by_key": {
        "fp-a": {"build_s": 2.0, "hits": 1},
        "fp-b": {"build_s": 4.0, "hits": 0},
        "fp-c": {"build_s": None, "hits": 0},  # never measured: skipped
    }}
    est = CostEstimator(lambda: snap, lambda: cache)
    warm = est.estimate(4, warm=True)
    assert warm == {"est_s": 2.0, "warm_cell_s": 0.5, "cold_build_s": 0.0,
                    "cells": 4, "warm": True}
    cold = est.estimate(4, warm=False)
    assert cold["cold_build_s"] == 3.0  # mean of the measured builds
    assert cold["est_s"] == 5.0

    name, v = est.verdict(4, 10.0, backlog_s=2.0, warm=True)
    assert name == "estimated"
    assert v["eta_s"] == 4.0 and v["backlog_s"] == 2.0
    assert v["deadline_s"] == 10.0
    # the backlog alone can make a deadline infeasible
    name, v = est.verdict(4, 3.0, backlog_s=2.0, warm=True)
    assert name == "infeasible" and v["eta_s"] == 4.0
    name, v = est.verdict(4, 1.0, warm=True)
    assert name == "infeasible" and v["eta_s"] == 2.0


def test_estimator_cold_build_falls_back_to_rolling_split():
    """No per-fingerprint build stats yet: the cold surcharge falls back
    to build seconds per cold request from the rolling split — guarded
    when no cold request has ever finished."""
    snap = {
        "cells": {"done": 4},
        "split": {"execute_s": 2.0, "build_s": 6.0},
        "requests": {"cold": 2},
    }
    est = CostEstimator(lambda: snap, lambda: None)
    assert est.cold_build_s() == 3.0
    assert est.estimate(2, warm=False)["est_s"] == 4.0
    no_cold = dict(snap, requests={"cold": 0})
    est = CostEstimator(lambda: no_cold, lambda: {})
    assert est.cold_build_s() == 0.0
