"""Real-dataset ingestion paths, executed on byte-exact on-disk formats.

Zero egress means the actual MNIST/CIFAR archives cannot be fetched, so
these tests synthesize files in the EXACT formats the loaders parse in
production — IDX2/IDX3 (gzipped and raw, big-endian magic + dims, reference
counterpart ``src/blades/datasets/mnist.py:46-70``) and CIFAR python-pickle
batches inside the official tar layout (``cifar10.py:73-101``) — then run
the full pipeline: parse -> partition -> FLDataset -> one attacked training
round. When a user drops in the real files, this is the code that runs,
already exercised end to end.
"""

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

from blades_tpu.datasets import CIFAR10, MNIST
from blades_tpu.datasets.cifar100 import CIFAR100


def _write_idx(tmp, gz=True):
    rng = np.random.RandomState(0)
    sets = {
        "train": (rng.randint(0, 256, (120, 28, 28), dtype=np.uint8),
                  rng.randint(0, 10, 120).astype(np.uint8)),
        "t10k": (rng.randint(0, 256, (40, 28, 28), dtype=np.uint8),
                 rng.randint(0, 10, 40).astype(np.uint8)),
    }
    op = (lambda p: gzip.open(p, "wb")) if gz else (lambda p: open(p, "wb"))
    ext = ".gz" if gz else ""
    for split, (x, y) in sets.items():
        with op(os.path.join(tmp, f"{split}-images-idx3-ubyte{ext}")) as f:
            f.write(struct.pack(">IIII", 2051, len(x), 28, 28))
            f.write(x.tobytes())
        with op(os.path.join(tmp, f"{split}-labels-idx1-ubyte{ext}")) as f:
            f.write(struct.pack(">II", 2049, len(y)))
            f.write(y.tobytes())
    return sets


@pytest.mark.parametrize("gz", [True, False])
def test_mnist_idx_roundtrip(tmp_path, gz):
    sets = _write_idx(str(tmp_path), gz=gz)
    ds = MNIST(data_root=str(tmp_path), num_clients=4, train_bs=8, cache=False)
    tx, ty, ex, ey = ds.load_raw()
    np.testing.assert_array_equal(tx[..., 0], sets["train"][0])
    np.testing.assert_array_equal(ty, sets["train"][1].astype(np.int32))
    np.testing.assert_array_equal(ex[..., 0], sets["t10k"][0])
    np.testing.assert_array_equal(ey, sets["t10k"][1].astype(np.int32))


def test_mnist_idx_to_training_round(tmp_path):
    """IDX files -> partition -> FLDataset -> one attacked federated round."""
    from blades_tpu import Simulator

    _write_idx(str(tmp_path))
    ds = MNIST(data_root=str(tmp_path), num_clients=4, train_bs=8, cache=False)
    sim = Simulator(dataset=ds, aggregator="median", num_byzantine=1,
                    attack="ipm", log_path=str(tmp_path / "out"), seed=0)
    sim.run("mlp", global_rounds=1, local_steps=1, train_batch_size=8,
            validate_interval=1)


def _write_cifar(tmp, n_train_per_batch=20, n_test=20, coarse=False):
    rng = np.random.RandomState(1)
    base = os.path.join(tmp, "cifar-10-batches-py")
    os.makedirs(base, exist_ok=True)
    batches = {}
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        n = n_test if name == "test_batch" else n_train_per_batch
        x = rng.randint(0, 256, (n, 3 * 32 * 32), dtype=np.uint8)
        y = rng.randint(0, 10, n).tolist()
        with open(os.path.join(base, name), "wb") as f:
            pickle.dump({b"data": x, b"labels": y}, f)
        batches[name] = (x, y)
    return base, batches


def test_cifar10_pickle_batches_roundtrip(tmp_path):
    base, batches = _write_cifar(str(tmp_path))
    ds = CIFAR10(data_root=str(tmp_path), num_clients=5, train_bs=8,
                 cache=False)
    tx, ty, ex, ey = ds.load_raw()
    assert tx.shape == (100, 32, 32, 3) and tx.dtype == np.uint8
    assert ex.shape == (20, 32, 32, 3)
    # NHWC transpose of the row-major CHW on-disk layout, first image
    first = batches["data_batch_1"][0][0].reshape(3, 32, 32).transpose(1, 2, 0)
    np.testing.assert_array_equal(tx[0], first)
    np.testing.assert_array_equal(ey, np.asarray(batches["test_batch"][1]))


def test_cifar10_tar_extraction(tmp_path):
    """The official tarball layout is auto-extracted on first use."""
    inner = tmp_path / "stage"
    inner.mkdir()
    base, _ = _write_cifar(str(inner))
    tar_path = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(base, arcname="cifar-10-batches-py")
    ds = CIFAR10(data_root=str(tmp_path), num_clients=5, train_bs=8,
                 cache=False)
    tx, ty, ex, ey = ds.load_raw()
    assert tx.shape == (100, 32, 32, 3)


def test_cifar100_fine_labels(tmp_path):
    """CIFAR-100 stores 'fine_labels'; loader must read them."""
    rng = np.random.RandomState(2)
    base = os.path.join(str(tmp_path), "cifar-100-python")
    os.makedirs(base)
    for name, n in (("train", 40), ("test", 20)):
        x = rng.randint(0, 256, (n, 3 * 32 * 32), dtype=np.uint8)
        y = rng.randint(0, 100, n).tolist()
        with open(os.path.join(base, name), "wb") as f:
            pickle.dump({b"data": x, b"fine_labels": y}, f)
    ds = CIFAR100(data_root=str(tmp_path), num_clients=4, train_bs=8,
                  cache=False)
    tx, ty, ex, ey = ds.load_raw()
    assert tx.shape == (40, 32, 32, 3)
    assert int(ty.max()) <= 99 and ty.dtype == np.int32


def test_missing_data_raises_actionable_error(tmp_path):
    with pytest.raises(FileNotFoundError, match="no network downloads"):
        MNIST(data_root=str(tmp_path / "nope"), cache=False).load_raw()
    with pytest.raises(FileNotFoundError, match="no network downloads"):
        CIFAR10(data_root=str(tmp_path / "nope"), cache=False).load_raw()
