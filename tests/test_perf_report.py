"""Cross-run perf-report tests: the one-JSON-line contract, reproduction
of the committed PR 5/PR 6 headline numbers from the artifacts the repo
already carries, and the --check regression gate (pass on the committed
baseline, fail on a synthetic regression).

Reference counterpart: none — the reference publishes no numbers
(BASELINE.md) and has no cross-run tooling at all.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import perf_report  # noqa: E402


def _run_cli(args):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         *args],
        capture_output=True, text=True, timeout=120,
    )
    return proc


def test_one_json_line_and_committed_numbers():
    """CLI contract + acceptance: exactly one stdout line, parseable, and
    it reproduces the PR 5 block speedup (2.72x) and the PR 6 streaming
    evidence (dense K=10^4 OOM, 82.5 MB streaming peak) from the
    committed artifacts."""
    proc = _run_cli([])
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["metric"] == "perf_report"
    assert payload["rows"] >= 10  # the committed artifact set is rich
    assert payload["block_speedup"] == pytest.approx(2.72, abs=0.01)
    assert payload["dense_oom_at_k10000"] is True
    assert payload["streaming_k10000_peak_update_bytes"] == 82512800
    assert payload["headline_tpu_rps"] == pytest.approx(1.2556)
    assert payload["ok"] is True


def test_check_passes_on_committed_baseline():
    proc = _run_cli(["--check"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout.strip())
    assert payload["regressions"] == [] and payload["ok"] is True
    assert payload["checked_against"].endswith("baseline.json")


def test_check_fails_on_synthetic_regression(tmp_path):
    """Acceptance: --check exits nonzero on a regressed input — the
    committed block pair with block64 throughput collapsed."""
    rb = tmp_path / "results" / "round_block"
    rb.mkdir(parents=True)
    for name in ("block1.json", "block64.json"):
        payload = json.load(
            open(os.path.join(REPO, "results", "round_block", name))
        )
        if name == "block64.json":
            payload["rounds_per_sec"] = payload["rounds_per_sec"] / 3.0
        json.dump(payload, open(rb / name, "w"))
    proc = _run_cli([
        "--repo", str(tmp_path), "--check",
        "--baseline",
        os.path.join(REPO, "results", "perf_report", "baseline.json"),
    ])
    assert proc.returncode == 1
    payload = json.loads(proc.stdout.strip())
    assert payload["ok"] is False
    assert any("rounds_per_sec" in r for r in payload["regressions"])
    assert any("block_speedup" in r for r in payload["regressions"])
    # a missing baseline is an explicit failure, not a silent pass
    proc = _run_cli(["--repo", str(tmp_path), "--check",
                     "--baseline", str(tmp_path / "nope.json")])
    assert proc.returncode == 1
    assert "no baseline" in json.loads(proc.stdout.strip())["regressions"][0]


def test_markdown_and_artifacts_out(tmp_path):
    proc = _run_cli(["--out", str(tmp_path / "pr"), "--markdown"])
    assert proc.returncode == 0
    assert "| run |" in proc.stderr  # table on stderr, never stdout
    md = open(tmp_path / "pr" / "trajectory.md").read()
    assert "round_block/block64" in md and "block_speedup" in md
    report = json.load(open(tmp_path / "pr" / "report.json"))
    assert report["block_speedup"] == pytest.approx(2.72, abs=0.01)
    assert any(
        r["name"] == "streaming_k/k10000_streaming_16gib"
        for r in report["trajectory"]
    )


def test_trace_ingestion(tmp_path):
    """A per-run telemetry.jsonl folds into the trajectory with
    rounds/sec from the round walls, compile counters and peak bytes."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from blades_tpu.telemetry import Recorder

    log = tmp_path / "myrun"
    log.mkdir()
    rec = Recorder(enabled=True, path=str(log / "telemetry.jsonl"))
    rec.counter("xla.compiles", 4)
    rec.gauge("engine.peak_update_bytes", 5000)
    for rnd in (1, 2):
        rec.round_record(rnd, wall_s=0.5)
    rec.close()
    rows = perf_report.ingest_traces([str(log / "telemetry.jsonl")])
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "trace/myrun"
    assert row["rounds_per_sec"] == pytest.approx(2.0)
    assert row["compiles"] == 4 and row["peak_update_bytes"] == 5000


def test_async_rows_ingested_non_headline():
    """PR 10: the committed buffered-async bench rows
    (results/asyncfl/rows.jsonl) fold into the trajectory with their
    async fields and surface as the `async_bench` derived entry — while
    the sync headline derived numbers are computed exactly as before
    (async rows are labeled, never the headline)."""
    report = perf_report.build_report(REPO, [])
    rows = [r for r in report["rows"] if r.get("async")]
    assert rows, "committed async rows missing from the trajectory"
    for r in rows:
        # child-payload rows carry the asyncM label in their row name
        # (the parent ladder's `config` label is the other spelling)
        assert r["name"].startswith("asyncfl/") and "asyncM" in r["name"]
        assert r.get("buffer_m") is not None
        assert r.get("agg_fires_per_round") is not None
    ab = report["derived"]["async_bench"]
    assert ab["rows"] == len(rows)
    assert ab["best_rounds_per_sec"] > 0
    # the sync headline gate's inputs are untouched by the async rows
    assert report["derived"]["block_speedup"] == 2.72


def test_committed_trajectory_artifacts_fresh():
    """The committed results/perf_report/ artifacts exist and agree with
    a fresh in-process report over the same repo (the trajectory is
    regenerable, not hand-typed)."""
    report = perf_report.build_report(REPO, [])
    derived = report["derived"]
    committed = json.load(
        open(os.path.join(REPO, "results", "perf_report", "report.json"))
    )
    assert committed["block_speedup"] == derived["block_speedup"]
    assert (
        committed["streaming_k10000_peak_update_bytes"]
        == derived["streaming_k10000_peak_update_bytes"]
    )
    baseline = json.load(
        open(os.path.join(REPO, "results", "perf_report", "baseline.json"))
    )
    assert baseline["derived"]["block_speedup"] == derived["block_speedup"]
    # the docs section was regenerated from the same data
    docs = open(os.path.join(REPO, "docs", "performance.md")).read()
    assert perf_report.DOCS_BEGIN in docs
    assert "`block_speedup` = 2.72" in docs


def test_resume_overhead_guard(tmp_path):
    """The resume-overhead guard (this PR): a resume that executed only
    the remaining cells passes; one that re-executed recovered cells —
    including the sharpest case, a fully-complete sweep resumed again —
    is a regression."""
    ok_trace = str(tmp_path / "ok.jsonl")
    with open(ok_trace, "w") as f:
        f.write(json.dumps({"t": "resume", "sweep": "certify",
                            "skipped": 3, "total": 5}) + "\n")
        for i in (4, 5):
            f.write(json.dumps({"t": "sweep", "sweep": "certify",
                                "cell": f"c{i}", "wall_s": 1.0,
                                "i": i, "total": 5}) + "\n")
    stats = perf_report.sweep_resume_stats([ok_trace])
    assert stats == [{"trace": ok_trace, "sweep": "certify",
                      "skipped": 3, "total": 5, "executed": 2,
                      "program_builds": 0, "programs_built": []}]
    assert perf_report.check_resume_overhead(stats) == []

    # resumed re-emits don't count as executed
    reemit_trace = str(tmp_path / "reemit.jsonl")
    with open(reemit_trace, "w") as f:
        f.write(json.dumps({"t": "resume", "sweep": "certify",
                            "skipped": 5, "total": 5}) + "\n")
        for i in range(1, 6):
            f.write(json.dumps({"t": "sweep", "sweep": "certify",
                                "cell": f"c{i}", "wall_s": 0.0, "i": i,
                                "total": 5, "resumed": True}) + "\n")
    stats = perf_report.sweep_resume_stats([reemit_trace])
    assert stats[0]["executed"] == 0
    assert perf_report.check_resume_overhead(stats) == []

    bad_trace = str(tmp_path / "bad.jsonl")
    with open(bad_trace, "w") as f:
        f.write(json.dumps({"t": "resume", "sweep": "certify",
                            "skipped": 5, "total": 5}) + "\n")
        f.write(json.dumps({"t": "sweep", "sweep": "certify", "cell": "c1",
                            "wall_s": 1.0, "i": 6, "total": 5}) + "\n")
    violations = perf_report.check_resume_overhead(
        perf_report.sweep_resume_stats([bad_trace])
    )
    assert len(violations) == 1 and "re-executed" in violations[0]


def test_check_gates_resume_overhead_via_cli(tmp_path):
    """--check folds the resume guard into the regression list: a trace
    with resume overhead fails the gate even though every baseline
    metric is healthy."""
    bad_trace = str(tmp_path / "sweep_trace.jsonl")
    with open(bad_trace, "w") as f:
        f.write(json.dumps({"t": "resume", "sweep": "certify",
                            "skipped": 4, "total": 4}) + "\n")
        f.write(json.dumps({"t": "sweep", "sweep": "certify", "cell": "x",
                            "wall_s": 1.0, "i": 5, "total": 4}) + "\n")
    proc = _run_cli(["--check", "--trace", bad_trace])
    assert proc.returncode == 1, proc.stdout
    payload = json.loads(proc.stdout.splitlines()[-1])
    assert not payload["ok"]
    assert any("resume overhead" in r for r in payload["regressions"])
