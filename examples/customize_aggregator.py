"""
Customization of aggregation scheme
===================================

Reference intent: ``src/blades/examples/todo_customize_aggregator.py`` (an
unfinished stub upstream; the accepted surfaces are the callable path in
``simulator.py:110-116`` and subclassing ``_BaseAggregator``,
``aggregators/mean.py:9-40``). This framework accepts both, working:

1. a **bare callable** ``[K, D] updates -> [D] aggregate`` — wrapped
   automatically, traced into the jitted round program;
2. an :class:`blades_tpu.aggregators.Aggregator` **subclass** — full
   control, including explicit cross-round state (the jit-compatible
   replacement for the reference's mutable-``self`` aggregators).

Both are demonstrated against 4/12 IPM attackers and compared to plain
mean. The subclass implements a norm-capped mean: each update's L2 norm is
clipped to a running median of past round norms (a simplified
centered-clipping flavor with real state threading).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

import jax.numpy as jnp  # noqa: E402

from blades_tpu.aggregators.base import Aggregator  # noqa: E402
from blades_tpu.datasets import Synthetic  # noqa: E402
from blades_tpu.simulator import Simulator  # noqa: E402
from blades_tpu.utils.logging import read_stats  # noqa: E402

ROUNDS = int(os.environ.get("CA_ROUNDS", 20))
STEPS = int(os.environ.get("CA_STEPS", 10))


def trimmed_like_callable(updates):
    """Surface 1: a plain function. Coordinate-wise midhinge: mean of the
    25th and 75th percentile per coordinate — cheap, outlier-resistant."""
    lo = jnp.percentile(updates, 25, axis=0)
    hi = jnp.percentile(updates, 75, axis=0)
    return 0.5 * (lo + hi)


class NormCappedMean(Aggregator):
    """Surface 2: an Aggregator subclass with explicit cross-round state.

    State = running estimate of the honest update norm; each round every
    update is rescaled to at most that norm before averaging, then the
    estimate moves toward this round's median norm. The state threading
    (instead of mutating ``self``) is what lets the defense live inside
    the compiled round program.
    """

    stateful = True

    def init_state(self, num_clients, dim):
        return jnp.asarray(1.0, jnp.float32)  # initial norm cap

    def aggregate(self, updates, state=(), **ctx):
        cap = state
        norms = jnp.linalg.norm(updates, axis=1)
        scale = jnp.minimum(1.0, cap / jnp.maximum(norms, 1e-12))
        clipped = updates * scale[:, None]
        new_cap = 0.7 * cap + 0.3 * jnp.median(norms)
        return clipped.mean(axis=0), new_cap

    def __repr__(self):
        return "NormCappedMean"


def run(agg, tag):
    ds = Synthetic(num_clients=12, train_size=2400, test_size=480,
                   noise=0.3, cache=False)
    log = os.path.join(os.environ.get("CA_OUT", "./outputs"), f"ca_{tag}")
    sim = Simulator(ds, num_byzantine=4, attack="ipm", aggregator=agg,
                    log_path=log, seed=1)
    sim.run(model="mlp", global_rounds=ROUNDS, local_steps=STEPS,
            server_lr=1.0, client_lr=0.1, validate_interval=ROUNDS)
    top1 = read_stats(log, type_filter="test")[-1]["top1"]
    print(f"{tag:16s} final top-1 = {top1:.3f}")
    return top1


if __name__ == "__main__":
    run("mean", "mean")
    run(trimmed_like_callable, "callable")
    run(NormCappedMean(), "subclass")
