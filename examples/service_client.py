"""Simulation service: submit experiments to a warm, crash-tolerant server.

Starts the long-lived simulation service (``blades_tpu/service``,
``scripts/serve.py``) as a subprocess, then drives it as a client over
its unix-domain socket:

1. a ``probe`` request — stdlib-only cells, served before jax is even
   imported in the server (health checks and chaos drills use these) —
   then the same with a tenant label, a priority class, and a deadline
   (the PR 17 multi-tenant scheduler: fair-share across tenants,
   deadline-aware admission, cell-boundary preemption);
2. a ``probe`` request carrying a poison cell — quarantined with an
   attributable error while its sibling cells complete (the PR 13
   resilient ladder, request-scoped);
3. two IDENTICAL ``simulate`` requests — real federated rounds on the
   seeded synthetic dataset; the second is served from the warm
   ``EngineCache`` with zero new compiles and must return bit-identical
   results (the warm-serving claim ``perf_report.py --check`` gates);
4. a live health snapshot (``op: status``) and the rolling serving
   metrics (``op: metrics`` — warm/cold request counts, the queue-wait/
   build/execute split, warm p99; ``blades_tpu/telemetry/reqpath.py``),
   then a graceful drain — the server finishes everything admitted and
   exits 0.

Every admitted request is journaled to an on-disk spool first, so a
SIGKILLed server replays it on relaunch under ``BLADES_RESUME=1`` and
executes only the unjournaled cells (docs/robustness.md "Simulation
service").
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default=os.path.join(REPO, "outputs", "service_demo"))
    p.add_argument("--rounds", type=int,
                   default=int(os.environ.get("SC_ROUNDS", "2")))
    args = p.parse_args()

    from blades_tpu.service.client import ServiceClient
    from blades_tpu.service.protocol import socket_path_for

    server = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"), "start",
         "--out", args.out, "--devices", "1", "--base-delay", "0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    client = ServiceClient(
        socket_path_for(args.out), timeout=600,
        connect_retries=50, connect_delay_s=0.2,
    )
    try:
        _drive(client, args)
        print("drain ->", json.dumps(client.drain()))
        out, _ = server.communicate(timeout=120)
        print("server exit:", server.returncode)
        print("server summary:", out.strip())
    finally:
        # a failure anywhere above must not leak a live server holding
        # the socket (the doc build executes this on a 1-core box)
        if server.poll() is None:
            server.kill()
            server.communicate()


def _drive(client, args) -> None:
    print("ping ->", json.dumps(client.ping()))

    probe = client.submit({"kind": "probe", "cells": [
        {"label": "hello", "op": "ok", "value": 42},
    ]})
    print("probe ->", json.dumps(probe["cells"]))

    # multi-tenant scheduling (blades_tpu/service/scheduler.py): requests
    # carry a tenant label, a priority class, and optionally a deadline —
    # the scheduler fair-shares tenants, preempts batch work at cell
    # boundaries for interactive requests, and rejects deadlines it
    # cannot meet (`rejected: deadline_infeasible`) before spooling
    tenant = client.submit(
        {"kind": "probe",
         "cells": [{"label": "urgent", "op": "ok", "value": 7}]},
        client="alice", priority="interactive", deadline_s=30.0,
    )
    print("tenant probe ->", json.dumps(tenant["cells"]))

    poison = client.submit({"kind": "probe", "cells": [
        {"label": "good", "op": "ok", "value": 1},
        {"label": "bad", "op": "fail", "message": "intentionally poisoned"},
    ]})
    bad = next(c for c in poison["cells"] if c["label"] == "bad")
    good = next(c for c in poison["cells"] if c["label"] == "good")
    print(f"poison -> bad quarantined ({bad['error_type']}), "
          f"good served: {json.dumps(good['result'])}")

    simulate = {"kind": "simulate", "cells": [
        {"label": "mean", "agg": "mean", "rounds": args.rounds, "seed": 11},
        {"label": "median", "agg": "median", "rounds": args.rounds,
         "seed": 11},
    ]}
    cold = client.submit(simulate, timeout=600)
    warm = client.submit(simulate, timeout=600)
    print("simulate (cold) ->", json.dumps(cold["cells"]))
    print("warm repeat bit-identical:", cold["cells"] == warm["cells"])

    status = client.status()
    print("status -> served={served} rejected={rejected} "
          "quarantined_requests={quarantined_requests}".format(**status))

    # request-path accounting (telemetry/reqpath.py): the rolling
    # serving metrics — warm/cold classification, the queue-wait /
    # build / execute split, warm p99 — live over `op: metrics`
    metrics = client.metrics()
    split = metrics["split"]
    print("metrics -> warm={warm} cold={cold}".format(**metrics["requests"]))
    print(f"metrics -> queue_wait_share={split['queue_wait_share']}, "
          f"warm p99 <= {metrics['latency']['warm'].get('p99_s')}s")
    # the scheduler rollup: preemptions taken, admission verdicts, and
    # the per-priority-class queue-depth high-water marks
    print("metrics -> sched =", json.dumps(metrics["sched"]))


if __name__ == "__main__":
    main()
