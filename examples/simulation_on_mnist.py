"""Simulation on MNIST: 5-aggregator sweep under the IPM attack.

Port of the reference's ``src/blades/examples/Simulation on MNIST.py``:
20 clients, 8 Byzantine running IPM with epsilon=100, sweeping the
aggregators {mean, trimmedmean, geomed, median, clippedclustering} for 10
global rounds of 10 local steps, then parsing each run's stats log
(one dict per line, ``_meta.type == 'test'`` records — the reference's
``read_json``, lines 69-83) and plotting the accuracy curves side by side.

Expected shape (matches the IPM paper, "Fall of Empires"): ``mean`` is
reversed outright (epsilon=100 makes the aggregate -39x the honest mean);
coordinate-wise ``median``/``trimmedmean`` are *subtly* reversed — their
output keeps a negative inner product with the true gradient, the attack's
namesake result — while ``geomed`` and ``clippedclustering`` stay aligned
and train.

Data: real MNIST IDX files under ``--data-root`` when present, else the
:class:`Synthetic` stand-in (zero-egress environments).

Usage: ``python examples/simulation_on_mnist.py [--rounds 10] [--out DIR]``
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

# reference sweep table ("Simulation on MNIST.py" lines 49-55)
AGGS = {
    "mean": {},
    "trimmedmean": {"num_byzantine": 8},
    "geomed": {},
    "median": {},
    "clippedclustering": {},
}

# categorical palette, fixed slot order (docs/assets house style)
COLORS = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100", "#e87ba4"]


def read_test_records(log_root: str):
    """The ``test`` records of a run's stats log (reference ``read_json``,
    "Simulation on MNIST.py" lines 69-83)."""
    from blades_tpu.utils.logging import read_stats

    return read_stats(log_root, type_filter="test")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--data-root", default=os.path.join(REPO, "data"))
    p.add_argument("--out", default=os.path.join(REPO, "results", "mnist_sweep"))
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--seeds", type=int, nargs="+", default=[1],
                   help="repeat the sweep per seed; finals reported as "
                        "mean [min-max] in summary.json")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from blades_tpu import Simulator
    from examples.convergence_config1 import build_dataset, seed_stats

    curves = {}
    finals = {agg: {} for agg in AGGS}
    for agg, agg_kws in AGGS.items():
        for seed in args.seeds:
            tag = f"{agg}_logs" if seed == args.seeds[0] else f"{agg}_s{seed}_logs"
            ds, kind = build_dataset(args.data_root, num_clients=20, seed=seed)
            sim = Simulator(
                dataset=ds,
                aggregator=agg,
                aggregator_kws=agg_kws,
                num_byzantine=8,
                attack="ipm",
                attack_kws={"epsilon": 100},
                log_path=os.path.join(args.out, tag),
                seed=seed,
            )
            sim.run(
                model="mlp",
                server_optimizer="SGD",
                client_optimizer="SGD",
                loss="crossentropy",
                global_rounds=args.rounds,
                local_steps=10,
                server_lr=1.0,
                client_lr=0.1,
            )
            tests = read_test_records(os.path.join(args.out, tag))
            finals[agg][seed] = tests[-1]["top1"]
            if seed == args.seeds[0]:
                curves[agg] = tests
            print(f"{agg} seed {seed}: final top1 = {tests[-1]['top1']:.4f}"
                  f"  ({kind})")

    import json

    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(
            {
                "config": f"20 clients, 8xIPM eps=100, {args.rounds} rounds "
                          "x 10 local steps",
                "seeds": args.seeds,
                "final_top1": {
                    a: seed_stats(v.values()) for a, v in finals.items()
                },
                "final_top1_per_seed": finals,
            },
            f, indent=2,
        )

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4.2), dpi=150)
    for color, (agg, tests) in zip(COLORS, curves.items()):
        ax.plot(
            [t["Round"] for t in tests],
            [100.0 * t["top1"] for t in tests],
            lw=2, color=color, label=agg,
        )
    ax.set_xlabel("Round")
    ax.set_ylabel("Test top-1 accuracy (%)")
    ax.set_title("20 clients, 8×IPM (ε=100): aggregator sweep")
    ax.grid(True, color="#e6e6e3", lw=0.6)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    ax.legend(frameon=False, loc="lower right", ncols=2)
    fig.tight_layout()
    out_png = os.path.join(args.out, "mnist_sweep.png")
    fig.savefig(out_png)
    print("plot:", out_png)


if __name__ == "__main__":
    main()
