"""Long-context text classification: ring vs Ulysses sequence parallelism.

The reference caps attention at <=256 tokens on one device
(``cctnets/utils/transformers.py:8-37``). Here the token axis of
``long_text_transformer`` is sharded over a device mesh and every encoder
layer runs exact sequence-parallel attention, with two interchangeable
collective schedules (same logits up to fp tolerance, verified below
against the dense single-device model):

- ``seq_parallel="ring"`` (``ops/ring_attention.py``): K/V blocks rotate
  via ``lax.ppermute``; O(N/P) activation memory — the extreme-N choice.
- ``seq_parallel="ulysses"`` (``ops/ulysses.py``): two ``all_to_all``
  reshards bracket a head-parallel local attention — bulk ICI traffic,
  no per-step recurrence; needs heads divisible by the axis size.

Env knobs: ``LC_SEQ`` (sequence length, default 512), ``LC_BATCH``,
``LC_DEVICES`` (virtual CPU devices when no mesh-capable backend is up).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import force_virtual_cpu  # noqa: E402

N_DEV = int(os.environ.get("LC_DEVICES", 8))
force_virtual_cpu(N_DEV)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from blades_tpu.models import long_text_transformer  # noqa: E402


def main() -> None:
    seq = int(os.environ.get("LC_SEQ", 512))
    batch = int(os.environ.get("LC_BATCH", 2))
    mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("seq",))

    kw = dict(num_classes=4, num_heads=8, word_embedding_dim=128)
    dense = long_text_transformer(mesh=None, **kw)
    ring = long_text_transformer(mesh=mesh, seq_parallel="ring", **kw)
    ulysses = long_text_transformer(mesh=mesh, seq_parallel="ulysses", **kw)

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq), 0, 1000)
    lens = jax.random.randint(jax.random.fold_in(key, 1), (batch, 1), seq // 2, seq + 1)
    mask = jnp.arange(seq)[None, :] < lens

    params = dense.init(jax.random.PRNGKey(1), tokens, mask)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    ref = dense.apply(params, tokens, mask)
    print(f"seq_len={seq} batch={batch} devices={N_DEV} params={n_params}")

    for name, model in (("ring", ring), ("ulysses", ulysses)):
        out = model.apply(params, tokens, mask)
        err = float(jnp.max(jnp.abs(out - ref)))
        ok = err < 3e-4
        print(f"{name:8s} max|logit - dense| = {err:.2e}  {'OK' if ok else 'MISMATCH'}")
        if not ok:
            raise SystemExit(f"{name} diverged from the dense oracle")
    print("both sequence-parallel schedules match the dense model")


if __name__ == "__main__":
    main()
