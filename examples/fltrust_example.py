"""
FLTrust: defense bootstrapped from one trusted client
=====================================================

Reference intent: ``src/blades/examples/todo_fltrusted_example.py`` (an
unfinished stub upstream; the working pieces are ``Fltrust``,
``aggregators/fltrust.py:8-38``, and ``set_trusted_clients``,
``simulator.py:143-151``). Here the full flow works end to end: mark ONE
client as the trusted root (it holds a clean dataset), aggregate with
FLTrust — every update is trust-scored by ReLU'd cosine similarity to the
trusted update and rescaled to its norm — and train through a
15/40-byzantine signflipping population that wrecks plain mean.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

from blades_tpu.datasets import Synthetic  # noqa: E402
from blades_tpu.simulator import Simulator  # noqa: E402
from blades_tpu.utils.logging import read_stats  # noqa: E402

ROUNDS = int(os.environ.get("FT_ROUNDS", 20))
STEPS = int(os.environ.get("FT_STEPS", 10))
K, BYZ = 40, 15


def run(aggregator, tag):
    ds = Synthetic(num_clients=K, train_size=4000, test_size=800,
                   noise=0.3, cache=False)
    log = os.path.join(os.environ.get("FT_OUT", "./outputs"), f"ft_{tag}")
    sim = Simulator(ds, num_byzantine=BYZ, attack="signflipping",
                    aggregator=aggregator, log_path=log, seed=1)
    # the trusted root must be an HONEST client (byzantine ids are the
    # first BYZ); FLTrust requires exactly one
    if aggregator == "fltrust":
        sim.set_trusted_clients([sim.get_clients()[-1].id()])
    sim.run(model="mlp", global_rounds=ROUNDS, local_steps=STEPS,
            server_lr=1.0, client_lr=0.1, validate_interval=ROUNDS)
    top1 = read_stats(log, type_filter="test")[-1]["top1"]
    print(f"{tag:8s} final top-1 = {top1:.3f}")
    return top1


if __name__ == "__main__":
    mean = run("mean", "mean")
    flt = run("fltrust", "fltrust")
    # at the full config the gap is decisive (measured 0.688 vs 0.106);
    # reduced doc-build configs (<15 rounds) are near chance for both and
    # a strict comparison there would be asserting on noise
    if ROUNDS >= 15:
        assert flt > mean + 0.2, (
            f"fltrust ({flt:.3f}) should decisively beat undefended mean "
            f"({mean:.3f}) under 37% signflipping"
        )
