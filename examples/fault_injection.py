"""Fault injection + graceful degradation: dropout, NaN clients, crash/resume.

Real federated deployments lose clients to dropout, receive stale updates
from stragglers, and occasionally ingest NaN payloads from broken hardware.
This demo runs that exact weather against three robust aggregators and shows
the run surviving all of it (``docs/robustness.md``):

1. a small MLP federation with **30% client dropout + 2 NaN-injecting
   faulty clients** under each of krum / median / trimmedmean — every round
   completes, the loss stays finite, and the per-round fault counters
   (participants, dropouts, non-finite exclusions) are read back from the
   telemetry trace;
2. the same run **killed mid-flight**: the crash autosave appears in the
   log dir and ``resume=True`` reproduces the uninterrupted run's final
   parameters bit-exactly.

The reference has no counterpart for any of this — it trains every client
every round and assumes every upload is well-formed
(``src/blades/simulator.py:213-244``).

Usage: ``python examples/fault_injection.py [--rounds 4] [--out DIR]
[--aggs krum median trimmedmean]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def fault_counts(log_path):
    """Per-round fault records from the run's telemetry trace."""
    trace = os.path.join(log_path, "telemetry.jsonl")
    if not os.path.exists(trace):  # BLADES_TELEMETRY=0
        return []
    with open(trace) as f:
        return [r for r in map(json.loads, f) if r.get("t") == "faults"]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--out", default=os.path.join(REPO, "results", "faults_demo"))
    p.add_argument("--aggs", nargs="+",
                   default=["krum", "median", "trimmedmean"])
    args = p.parse_args()

    import numpy as np

    from blades_tpu import FaultModel, Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.ops.pytree import ravel

    faults = FaultModel(
        dropout_rate=0.3,          # ~30% of clients miss any given round
        corrupt_clients=(0, 1),    # two permanently NaN-emitting clients
        corrupt_mode="nan",
    )

    def build(agg, sub, seed=0):
        return Simulator(
            dataset=Synthetic(num_clients=8, train_size=800, test_size=160,
                              noise=0.3, cache=False),
            aggregator=agg,
            aggregator_kws={"num_byzantine": 2} if agg != "median" else {},
            log_path=os.path.join(args.out, sub),
            seed=seed,
        )

    run_kw = dict(global_rounds=args.rounds, local_steps=2, client_lr=0.2,
                  server_lr=1.0, train_batch_size=8,
                  validate_interval=args.rounds)

    # -- 1. three defenses under dropout + NaN clients ----------------------
    for agg in args.aggs:
        sim = build(agg, agg)
        sim.run("mlp", fault_model=faults, **run_kw)
        ev = sim.evaluate(args.rounds, 64)
        assert np.isfinite(ev["Loss"]), f"{agg}: loss went non-finite!"
        recs = fault_counts(os.path.join(args.out, agg))
        excl = sum(r["excluded_nonfinite"] for r in recs)
        dropped = sum(r["dropped"] for r in recs)
        parts = [r["participants"] for r in recs]
        print(f"{agg:12s} loss={ev['Loss']:.4f} top1={ev['top1']:.3f}  "
              f"participants/round={parts}  dropped={dropped} "
              f"nan_rows_excluded={excl}")

    # -- 2. kill mid-run, resume bit-exactly --------------------------------
    agg = args.aggs[0]
    ref_sim = build(agg, "uninterrupted", seed=3)
    ref_sim.run("mlp", fault_model=faults, **run_kw)
    ref = np.asarray(ravel(ref_sim.server.state.params))

    kill_at = max(args.rounds // 2, 1)

    def killer(rnd, state, m):
        if rnd == kill_at:
            raise RuntimeError("simulated mid-run kill")

    crash_log = os.path.join(args.out, "crashed")
    crash_sim = build(agg, "crashed", seed=3)
    try:
        crash_sim.run("mlp", fault_model=faults, on_round_end=killer, **run_kw)
        raise AssertionError("the kill did not fire")
    except RuntimeError:
        pass
    autosave = os.path.join(crash_log, "autosave.npz")
    print(f"\nkilled at round {kill_at}; crash autosave written: "
          f"{os.path.exists(autosave)}")

    resumed = build(agg, "crashed", seed=3)  # same log dir -> same autosave
    resumed.run("mlp", fault_model=faults, resume=True, **run_kw)
    out = np.asarray(ravel(resumed.server.state.params))
    exact = bool(np.array_equal(ref, out))
    print(f"resumed rounds {kill_at + 1}..{args.rounds}; final params "
          f"bit-identical to the uninterrupted run: {exact}")
    assert exact, "resume was not bit-exact"


if __name__ == "__main__":
    main()
