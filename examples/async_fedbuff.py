"""Buffered-asynchronous rounds (FedBuff-style): arrivals, staleness, fires.

Production federated clients do not report in lockstep — they download the
model, train for however long their hardware takes, and report late. This
demo runs `blades_tpu/asyncfl`'s buffered-async semantics end to end
(``docs/robustness.md`` "Asynchronous scenarios"):

1. **degenerate equivalence** — ``buffer_m = K`` + zero-delay arrivals +
   constant weighting reproduces the synchronous run's final parameters
   bit-exactly (the invariant that anchors the async body to the sync
   engine);
2. **a staggered federation** — uniform arrival delays, first-M fire
   threshold, polynomial staleness weighting, 2 byzantine IPM clients
   under a median defense: the per-round ``async`` telemetry records
   (arrivals, buffer fill, fire flag, staleness moments) are read back
   from the trace and printed as a timeline;
3. **staleness-mode comparison** — the same scenario under constant /
   polynomial / cutoff weighting, showing fire cadence and final loss.

The reference has no counterpart for any of this — its simulator is
strictly synchronous (``src/blades/simulator.py:203-247``) and its async
aggregator classes are unreachable dead code. Protocol: FedBuff (Nguyen
et al., AISTATS 2022).

Usage: ``python examples/async_fedbuff.py [--rounds 6] [--out DIR]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def async_records(log_path):
    """Per-round ``async`` records from the run's telemetry trace."""
    trace = os.path.join(log_path, "telemetry.jsonl")
    if not os.path.exists(trace):  # BLADES_TELEMETRY=0
        return []
    with open(trace) as f:
        return [r for r in map(json.loads, f) if r.get("t") == "async"]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--out", default=os.path.join(REPO, "results", "async_demo"))
    args = p.parse_args()

    import numpy as np

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.ops.pytree import ravel

    def build(sub, seed=5):
        return Simulator(
            dataset=Synthetic(num_clients=8, train_size=800, test_size=160,
                              noise=0.3, cache=False),
            aggregator="median",
            attack="ipm",
            num_byzantine=2,
            log_path=os.path.join(args.out, sub),
            seed=seed,
        )

    run_kw = dict(global_rounds=args.rounds, local_steps=1, client_lr=0.2,
                  server_lr=1.0, train_batch_size=8,
                  validate_interval=args.rounds)

    # -- 1. degenerate equivalence: async(buffer_m=K, zero delay) == sync --
    sync = build("sync")
    sync.run("mlp", **run_kw)
    p_sync = np.asarray(ravel(sync.server.state.params))
    degen = build("degenerate")
    degen.run("mlp", async_config=dict(
        buffer_m=8, arrivals=dict(kind="zero"), staleness="constant",
    ), **run_kw)
    p_degen = np.asarray(ravel(degen.server.state.params))
    assert np.array_equal(p_sync, p_degen), "degenerate async != sync!"
    print("degenerate async (buffer_m=K, zero delays, constant) == sync: "
          "final params bit-identical\n")

    # -- 2. staggered arrivals + polynomial staleness weighting -------------
    asy = build("fedbuff")
    asy.run("mlp", async_config=dict(
        buffer_m=4, arrivals=dict(kind="uniform", max_delay=2),
        staleness="polynomial", alpha=0.5,
    ), **run_kw)
    ev = asy.evaluate(args.rounds, 64)
    assert np.isfinite(ev["Loss"]), "async run went non-finite!"
    print(f"fedbuff(m=4, uniform delays<=2, poly a=0.5)  "
          f"loss={ev['Loss']:.4f} top1={ev['top1']:.3f}")
    print("tick  arrivals  buffer  fired  aggregated  mean_tau")
    for r in async_records(os.path.join(args.out, "fedbuff")):
        print(f"{r['round']:4d}  {r['arrivals']:8d}  {r['buffer_count']:6d}"
              f"  {r['fired']:5d}  {r['aggregated']:10d}"
              f"  {r['mean_staleness']:8.2f}")
    fires = sum(r["fired"] for r in async_records(
        os.path.join(args.out, "fedbuff")))
    print(f"fires: {fires}/{args.rounds} ticks\n")

    # -- 3. staleness-mode comparison ---------------------------------------
    modes = [
        ("constant", dict(staleness="constant")),
        ("polynomial", dict(staleness="polynomial", alpha=0.5)),
        ("cutoff", dict(staleness="cutoff", cutoff=1)),
    ]
    for name, stale_kw in modes:
        sim = build(f"mode_{name}")
        sim.run("mlp", async_config=dict(
            buffer_m=4, arrivals=dict(kind="uniform", max_delay=2),
            **stale_kw,
        ), **run_kw)
        ev = sim.evaluate(args.rounds, 64)
        recs = async_records(os.path.join(args.out, f"mode_{name}"))
        fires = sum(r["fired"] for r in recs)
        excluded = sum(r["stale_excluded"] for r in recs)
        print(f"{name:10s} loss={ev['Loss']:.4f} fires={fires}"
              f" stale_excluded={excluded}")


if __name__ == "__main__":
    main()
