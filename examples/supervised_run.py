"""Run supervision: a hung run is heartbeat-killed and resumed bit-exactly.

This box's documented failure modes (a TPU tunnel that hangs backend init
forever, an XLA collective deadlock) never raise — the process just stops.
The run supervisor (``blades_tpu/supervision``, docs/robustness.md) turns
that into a bounded-time, self-recovering event, demonstrated end to end:

1. a reference run completes uninterrupted → final parameters saved;
2. the same run is launched **supervised** with a saboteur that hangs it
   hard at round 2 (after spawning a grandchild, like a real orphaned
   probe). The Simulator beats a heartbeat file at every round flush; the
   supervisor sees the beat go stale, kills the child's **entire process
   group** (SIGTERM → the crash autosave fires → SIGKILL; zero orphans),
   and relaunches with ``BLADES_RESUME=1``;
3. the relaunch resumes from the autosave and finishes — final parameters
   **bit-identical** to the uninterrupted run, with the attempt/kill/
   resume trail in the run's own ``telemetry.jsonl``.

Usage: ``python examples/supervised_run.py [--rounds 3] [--out DIR]``
(``--child`` is the internal supervised-workload mode).

Reference counterpart: none — the reference assumes a permanently healthy
Ray cluster (``src/blades/simulator.py:189-211``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def child_main(args) -> None:
    """The supervised workload: a small MLP federation with per-round
    checkpoints, hanging hard at ``--hang-at`` exactly once."""
    from blades_tpu.utils.platform import force_virtual_cpu

    force_virtual_cpu(1)

    import numpy as np

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.ops.pytree import ravel

    sentinel = os.path.normpath(args.out) + ".hang_fired"
    # fresh launch (not a supervised resume): clear a previous
    # invocation's sentinel or the rerun demo would never hang
    if os.environ.get("BLADES_RESUME") != "1" and os.path.exists(sentinel):
        os.unlink(sentinel)

    def saboteur(rnd, state, m):
        if args.hang_at and rnd == args.hang_at and not os.path.exists(sentinel):
            open(sentinel, "w").close()
            subprocess.Popen(["sleep", "600"])  # the orphan-to-be
            print(f"[child] hanging hard at round {rnd}", flush=True)
            time.sleep(600)

    sim = Simulator(
        dataset=Synthetic(num_clients=6, train_size=300, test_size=60,
                          noise=0.3, cache=False),
        aggregator="median",
        log_path=args.out,
        seed=7,
    )
    sim.run(
        "mlp",
        global_rounds=args.rounds, local_steps=1, train_batch_size=8,
        client_lr=0.2, server_lr=1.0, validate_interval=args.rounds,
        checkpoint_path=os.path.join(args.out, "ck"), checkpoint_interval=1,
        on_round_end=saboteur,
    )
    np.save(args.params_out, np.asarray(ravel(sim.server.state.params)))
    print("[child] run complete", flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--out", default=os.path.join(REPO, "results", "supervised_demo"))
    p.add_argument("--child", action="store_true")
    p.add_argument("--hang-at", type=int, default=0)
    p.add_argument("--params-out", default=None)
    args = p.parse_args()
    if args.child:
        child_main(args)
        return

    import numpy as np

    from blades_tpu.supervision import Supervisor

    hang_round = max(args.rounds - 1, 1)

    def child_cmd(out, params, hang):
        return [sys.executable, os.path.abspath(__file__), "--child",
                "--rounds", str(args.rounds), "--out", out,
                "--params-out", params, "--hang-at", str(hang)]

    # -- 1. uninterrupted reference ----------------------------------------
    ref_params = os.path.join(args.out, "ref_params.npy")
    subprocess.run(
        child_cmd(os.path.join(args.out, "ref"), ref_params, 0),
        check=True, cwd=REPO,
    )

    # -- 2. supervised run with a mid-run hard hang ------------------------
    sup_dir = os.path.join(args.out, "supervised")
    sup_params = os.path.join(args.out, "sup_params.npy")
    telemetry = os.path.join(sup_dir, "telemetry.jsonl")
    if os.path.exists(telemetry):
        os.unlink(telemetry)  # fresh demo: don't append to a prior trail
    result = Supervisor(
        child_cmd(sup_dir, sup_params, hang_round),
        heartbeat_timeout_s=8.0,     # round beats go stale -> group kill
        startup_grace_s=600.0,       # jax import + first compile window
        attempts=2,                  # one relaunch (with BLADES_RESUME=1)
        term_grace_s=8.0,            # SIGTERM window for the crash autosave
        telemetry_path=telemetry,
        cwd=REPO,
    ).run()

    print("\nattempt trail:")
    for a in result.attempts:
        print(f"  attempt {a.index}: {a.reason:16s} "
              f"degrade={list(a.degrade) or '-'} resumed={a.resumed} "
              f"orphans={len(a.survivors)}")
    assert result.ok, "supervised run did not recover"
    assert result.attempts[0].reason == "heartbeat_stale"
    assert result.attempts[0].survivors == (), "orphans survived the group kill"

    ref = np.load(ref_params)
    out = np.load(sup_params)
    exact = bool(np.array_equal(ref, out))
    print(f"resumed run final params bit-identical to uninterrupted: {exact}")
    assert exact

    with open(telemetry) as f:
        events = [r for r in map(json.loads, f) if r.get("t") == "supervisor"]
    print("supervisor telemetry trail:",
          [e["event"] for e in events])


if __name__ == "__main__":
    main()
