"""Run identity, the provenance ledger, and anomaly alerts.

Every entry point in this repo now mints a ``run_id`` (propagated to
child processes via ``BLADES_RUN_ID``/``BLADES_ATTEMPT``), stamps it on
every telemetry record, and appends a ``started`` -> ``finished``/
``crashed``/``killed`` pair to an append-only run ledger
(``results/ledger.jsonl`` by default) carrying the config fingerprint,
git sha, and environment fingerprint — so evidence artifacts are
addressable and comparable instead of anonymous JSONL files. A small
rule engine (``blades_tpu/telemetry/alerts.py``) watches the run's own
record streams live and emits schema-locked ``alert`` records on
divergence, breach storms, compile storms, or shrinking heartbeat
margins.

This demo runs three federations against a demo ledger:

1. a healthy run — ledger pair, run_id on every trace record;
2. the SAME config again — a different run_id but the same config
   fingerprint ("same experiment, different run" is a string equality,
   which is what lets ``trace_summary.py --compare`` refuse to diff
   unrelated runs);
3. a deliberately diverging run (absurd client LR) — the alert engine
   flags the non-finite/diverging loss in the trace as it happens.

It closes with the ledger query the ``scripts/runs.py`` CLI wraps.

Usage: ``python examples/run_ledger.py [--rounds 4] [--out DIR]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def _trace(log_dir):
    with open(os.path.join(log_dir, "telemetry.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--out", default=os.path.join(REPO, "results",
                                                 "ledger_demo"))
    args = p.parse_args()

    # point the ledger at the demo directory (the default is the repo's
    # results/ledger.jsonl; BLADES_LEDGER=0 disables entirely)
    ledger_path = os.path.join(args.out, "ledger.jsonl")
    os.environ["BLADES_LEDGER"] = ledger_path

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from blades_tpu.telemetry import ledger

    def run(log_dir, client_lr, rounds=None):
        sim = Simulator(
            dataset=Synthetic(
                num_clients=6, train_size=480, test_size=120, noise=0.3,
                cache=False,
            ),
            num_byzantine=1,
            attack="signflipping",
            aggregator="median",
            log_path=os.path.join(args.out, log_dir),
            seed=0,
        )
        rounds = rounds or args.rounds
        sim.run(
            "mlp", global_rounds=rounds, local_steps=1,
            client_lr=client_lr, train_batch_size=8,
            validate_interval=rounds,
        )
        return _trace(os.path.join(args.out, log_dir))

    healthy = run("healthy", client_lr=0.2)
    rerun = run("rerun", client_lr=0.2)
    # the loss-divergence rule compares two trailing windows of 3 rounds,
    # so the seeded blow-up needs at least 6 rounds to show itself
    diverged = run("diverging", client_lr=500.0,
                   rounds=max(8, args.rounds))

    # 1. every record of a run carries its run_id/attempt envelope
    rid = healthy[0]["run_id"]
    stamped = all(
        r.get("run_id") == rid and r.get("attempt") == 1 for r in healthy
    )
    print(f"healthy run {rid}: {len(healthy)} records, "
          f"all stamped with run_id/attempt: {stamped}")

    # 2. same experiment config -> same fingerprint, different run_id
    fp_a = healthy[0]["config_fingerprint"]
    fp_b = rerun[0]["config_fingerprint"]
    print(f"re-run of the same config: run_id {rerun[0]['run_id']} "
          f"(new), config fingerprint {fp_b} "
          f"({'SAME' if fp_a == fp_b else 'DIFFERENT'} as {fp_a})")
    fp_c = diverged[0]["config_fingerprint"]
    print(f"diverging run's fingerprint {fp_c} differs: {fp_c != fp_a}")

    # 3. the alert engine flagged the seeded divergence live, in-trace
    alerts = [r for r in diverged if r["t"] == "alert"]
    print(f"\nalerts on the diverging run ({len(alerts)}):")
    for a in alerts:
        print(f"  [{a['severity']}] {a['rule']}: {a['message']}")
    quiet = [r for r in healthy + rerun if r["t"] == "alert"]
    print(f"alerts on the two healthy runs: {len(quiet)}")

    # 4. the ledger knows every run's provenance and outcome
    print(f"\nledger {ledger_path}:")
    for run_row in ledger.pair_runs(ledger.read_ledger(ledger_path)):
        metrics = run_row.get("metrics") or {}
        print(f"  {run_row['run_id']} attempt {run_row['attempt']} "
              f"[{run_row['kind']}] config {run_row.get('config_fingerprint')} "
              f"code {str(run_row.get('code_version'))[:10]} -> "
              f"{run_row['outcome']} "
              f"({metrics.get('rounds_completed')} rounds)")


if __name__ == "__main__":
    main()
