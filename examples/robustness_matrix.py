"""Robustness matrix: every built-in attack against every major defense.

Beyond-parity evidence artifact (the reference's closest analogue is the
single-config sweep in ``Simulation on MNIST.py``): a grid of attacked
training runs — {none, noise, labelflipping, signflipping, alie, ipm} ×
{mean, median, trimmedmean, geomed, krum, clippedclustering, dnc,
signguard} — each run 20
clients (8 Byzantine) for ``--rounds`` rounds of 10 local steps on the
MNIST-shaped task, reporting final test top-1 per cell. One command, no
network, ~25 min on an 8-core CPU mesh.

Outputs: ``results/matrix/matrix.json`` (+ per-run stats logs) and a
heatmap at ``results/matrix/matrix.png``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

ATTACKS = ["none", "noise", "labelflipping", "signflipping", "alie", "ipm"]
AGGS = ["mean", "median", "trimmedmean", "geomed", "krum",
        "clippedclustering", "dnc", "signguard"]
K, BYZ = 20, 8


# defenses that take the attacker-budget assumption as a constructor arg;
# the defender's assumed f is held at the true BYZ for every cell
BUDGET_AGGS = {"trimmedmean", "krum", "dnc"}

# Per-cell expectations, checked by tests/test_matrix_summary.py — the matrix
# is a regression GATE, not just logs + a PNG. Bounds carry ~0.1 margin vs
# the committed 20-round seed-1 measurements to tolerate seed noise while
# still catching a defense that silently stops working (or an attack that
# silently stops biting). Notable rows: sign-symmetric defenses (median /
# trimmedmean / signguard) break under signflipping; Krum-family and
# distance-based defenses (median/trimmedmean/geomed/krum) collapse under
# IPM because the 8 byzantine rows are IDENTICAL (-eps * honest mean), give
# each other zero pairwise distance, and win every nearest-neighbor
# selection — DnC and clipped clustering are the only defenses that hold
# every row.
#   rule: ("min", x) = defense holds, top1 >= x
#         ("max", x) = attack wins,   top1 <= x
#         ("range", lo, hi) = degraded but not destroyed
#         ("band_rel", lo, d) = defense holds (top1 >= lo) BUT the attack
#             still measurably bites: top1 <= this column's "none" cell - d.
#             Used where absolute floors are too loose to catch an
#             attack-becomes-no-op regression (VERDICT r4 weak #5): ALIE's
#             measured damage on median/trimmedmean is -0.126/-0.119 at
#             seed 1 and replicates at -0.165/-0.160 (seed 2) and
#             -0.167/-0.161 (seed 3), so d=0.05 leaves seed room while a
#             stubbed-out ALIE (attacked == unattacked) fails the cell.
#             The other ALIE columns measured deltas within seed noise
#             (mean ~+0.05; geomed/krum sign-flip across seeds; dnc
#             slightly negative at every seed) — no relative bound is
#             supportable there, so they keep absolute floors. Floors sit
#             below the THREE-seed measured range (seeds 1-3 committed as
#             results/matrix{,_s2,_s3}) but far above a broken defense
#             (collapse ~0.07-0.25): e.g. dnc's lowest cell across seeds
#             is 0.612 (ipm, seed 3) vs its 0.58 floor.
EXPECTATIONS = {
    "none": {agg: ("min", 0.50) for agg in AGGS},
    "noise": {
        "mean": ("max", 0.30),
        **{a: ("min", 0.55) for a in
           ("median", "trimmedmean", "clippedclustering", "dnc",
            "signguard")},
        # geomed/krum measured [0.545, 0.607] across seeds 1-3 — floor
        # below that range, far above a broken defense (noise vs mean
        # collapses to ~0.09-0.11)
        "geomed": ("min", 0.52),
        "krum": ("min", 0.52),
    },
    "labelflipping": {
        "mean": ("range", 0.25, 0.55),
        "median": ("range", 0.25, 0.55),
        "trimmedmean": ("range", 0.25, 0.55),
        "geomed": ("min", 0.50),
        "krum": ("min", 0.50),
        "clippedclustering": ("min", 0.50),
        "dnc": ("min", 0.58),
        "signguard": ("range", 0.35, 0.70),
    },
    "signflipping": {
        "mean": ("max", 0.30),
        "median": ("max", 0.30),
        "trimmedmean": ("max", 0.30),
        "signguard": ("max", 0.30),
        "geomed": ("min", 0.50),
        "krum": ("min", 0.50),
        "clippedclustering": ("min", 0.50),
        "dnc": ("min", 0.58),
    },
    "alie": {
        **{a: ("min", 0.50) for a in AGGS},
        "median": ("band_rel", 0.48, 0.05),
        "trimmedmean": ("band_rel", 0.48, 0.05),
        # [0.492, 0.563] measured across seeds 1-3
        "clippedclustering": ("min", 0.47),
        "dnc": ("min", 0.58),
    },
    "ipm": {
        "mean": ("range", 0.10, 0.50),
        "median": ("max", 0.20),
        "trimmedmean": ("max", 0.20),
        "geomed": ("max", 0.20),
        "krum": ("max", 0.20),
        "signguard": ("range", 0.25, 0.60),
        "clippedclustering": ("min", 0.50),
        "dnc": ("min", 0.58),
    },
}


def evaluate_expectations(matrix):
    """Check every expectation against a measured matrix; returns (rows,
    all_ok) where rows carry per-cell verdicts for summary.json."""
    rows = []
    ok_all = True
    for attack, cells in EXPECTATIONS.items():
        for agg, rule in cells.items():
            value = matrix.get(attack, {}).get(agg)
            baseline = matrix.get("none", {}).get(agg)
            if value is None:
                ok = False
            elif rule[0] == "min":
                ok = value >= rule[1]
            elif rule[0] == "max":
                ok = value <= rule[1]
            elif rule[0] == "band_rel":
                ok = baseline is not None and (
                    rule[1] <= value <= baseline - rule[2]
                )
            else:
                ok = rule[1] <= value <= rule[2]
            ok_all = ok_all and ok
            rows.append(
                {"attack": attack, "agg": agg, "rule": list(rule),
                 "top1": value, "ok": bool(ok)}
            )
    return rows, ok_all


def run_cell(ds, attack: str, agg: str, rounds: int, out_dir: str,
             seed: int = 1) -> float:
    from blades_tpu import Simulator
    from blades_tpu.utils.logging import read_stats

    log_path = os.path.join(out_dir, f"{attack}__{agg}")
    sim = Simulator(
        dataset=ds,
        aggregator=agg,
        aggregator_kws={"num_byzantine": BYZ} if agg in BUDGET_AGGS else {},
        num_byzantine=0 if attack == "none" else BYZ,
        attack=None if attack == "none" else attack,
        log_path=log_path,
        seed=seed,
    )
    sim.run(
        model="mlp",
        global_rounds=rounds,
        local_steps=10,
        server_lr=1.0,
        client_lr=0.1,
        validate_interval=rounds,
    )
    return float(read_stats(log_path, type_filter="test")[-1]["top1"])


def plot(matrix, path: str) -> None:
    """Sequential single-hue heatmap, per-cell value labels."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    data = np.array([[matrix[a][g] for g in AGGS] for a in ATTACKS])
    fig, ax = plt.subplots(figsize=(9.5, 5), dpi=150)
    im = ax.imshow(data, cmap="Blues", vmin=0.0, vmax=1.0)
    ax.set_xticks(range(len(AGGS)), AGGS, rotation=30, ha="right")
    ax.set_yticks(range(len(ATTACKS)), ATTACKS)
    ax.set_xlabel("Aggregator (defense)")
    ax.set_ylabel("Attack (8 of 20 clients)")
    ax.set_title("Final test top-1 after attacked training")
    for i in range(len(ATTACKS)):
        for j in range(len(AGGS)):
            v = data[i, j]
            ax.text(j, i, f"{100 * v:.0f}", ha="center", va="center",
                    fontsize=8, color="white" if v > 0.55 else "#333")
    fig.colorbar(im, ax=ax, shrink=0.8, label="top-1 accuracy")
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--seed", type=int, default=1,
                   help="training seed per cell (dataset partition stays "
                        "seed-1 so cells differ only by trajectory)")
    p.add_argument("--out", default=os.path.join(REPO, "results", "matrix"))
    p.add_argument("--attacks", nargs="*", default=ATTACKS)
    p.add_argument("--aggs", nargs="*", default=AGGS)
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from examples.convergence_config1 import build_dataset

    ds, _ = build_dataset(os.path.join(REPO, "data"), num_clients=K, seed=1)

    # merge into any existing matrix so partial re-runs (e.g. one defense
    # column) refresh the committed artifact instead of truncating it
    matrix_path = os.path.join(args.out, "matrix.json")
    matrix = {}
    if os.path.exists(matrix_path):
        with open(matrix_path) as f:
            matrix = json.load(f)
        prev_rounds = matrix.get("_rounds")
        prev_seed = matrix.get("_seed", 1)
        if matrix and (prev_rounds != args.rounds or prev_seed != args.seed):
            # an existing file without _rounds has unknown provenance —
            # refuse that too rather than mislabel mixed-rounds cells
            sys.exit(
                f"refusing to merge --rounds {args.rounds} --seed "
                f"{args.seed} cells into a matrix recorded at "
                f"{prev_rounds} rounds, seed {prev_seed} ({matrix_path}); "
                "match both or use a fresh --out dir"
            )
    matrix["_rounds"] = args.rounds
    matrix["_seed"] = args.seed
    for attack in args.attacks:
        matrix.setdefault(attack, {})
        for agg in args.aggs:
            top1 = run_cell(ds, attack, agg, args.rounds, args.out, args.seed)
            matrix[attack][agg] = top1
            print(f"{attack:14s} x {agg:18s} -> top1 {top1:.3f}", flush=True)

    with open(matrix_path, "w") as f:
        json.dump(matrix, f, indent=2)
    if all(agg in matrix.get(a, {}) for a in ATTACKS for agg in AGGS):
        plot(matrix, os.path.join(args.out, "matrix.png"))
        print("plot:", os.path.join(args.out, "matrix.png"))
        rows, ok = evaluate_expectations(matrix)
        with open(os.path.join(args.out, "summary.json"), "w") as f:
            json.dump(
                {
                    "rounds": matrix["_rounds"],
                    "seed": matrix["_seed"],
                    # every krum cell uses the d^2 paper default; the
                    # reference-compat d^4 ranking is Krum(distance_power=4)
                    "krum_variant": "distance_power=2 (paper default)",
                    "all_ok": ok,
                    "cells": rows,
                },
                f, indent=1,
            )
        # BASELINE.md's third per-config metric ("attack success = accuracy
        # degradation vs no-attack run"): the attacked cell's top-1 drop
        # against the same defense's unattacked cell, positive = the attack
        # cost accuracy
        success = {
            a: {g: round(matrix["none"][g] - matrix[a][g], 4) for g in AGGS}
            for a in ATTACKS if a != "none" and a in matrix
        }
        with open(os.path.join(args.out, "attack_success.json"), "w") as f:
            json.dump(
                {
                    "definition": "delta_top1[attack][agg] = top1(none, agg)"
                                  " - top1(attack, agg); positive = attack"
                                  " succeeded by that many points",
                    "rounds": matrix["_rounds"],
                    "seed": matrix["_seed"],
                    "delta_top1": success,
                },
                f, indent=1,
            )
        bad = [r for r in rows if not r["ok"]]
        print(f"expectations: {len(rows) - len(bad)}/{len(rows)} ok")
        for r in bad:
            print(f"  FAIL {r['attack']} x {r['agg']}: top1={r['top1']} "
                  f"rule={r['rule']}")


if __name__ == "__main__":
    main()
