"""Streaming client axis: [chunk, D] peak update memory, K-independent.

The dense round materializes the full ``[K, D]`` post-attack update matrix
before aggregating — the client axis is capped by device memory. With
``streaming=True`` the round chunk-SCANS training and feeds ``[chunk, D]``
slabs into the aggregator's streaming reduction state
(``docs/performance.md``, "Memory scaling"), so K scales to 10^4-10^5
(``results/streaming_k/``). This demo runs the same small federation both
ways and shows:

1. the telemetry **memory gauges** (``engine.peak_update_bytes`` et al.)
   recording ``[K, D]`` for the dense run vs ``[chunk, D]`` for the
   streaming run;
2. the two runs agreeing on training (streaming trimmed-mean is the
   documented two-level form — chunk-local trim, then trim across chunk
   aggregates);
3. a non-divisible chunk count: the engine pads the final chunk instead of
   rejecting it.

Usage: ``python examples/streaming_clients.py [--rounds 3] [--out DIR]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def memory_gauges(log_path):
    with open(os.path.join(log_path, "telemetry.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("t") == "round":
                g = rec.get("gauges", {})
                if "engine.peak_update_bytes" in g:
                    return {
                        k.split(".", 1)[1]: g[k]
                        for k in (
                            "engine.peak_update_bytes",
                            "engine.client_chunks",
                            "engine.chunk_size",
                            "engine.streaming",
                        )
                    }
    return {}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--out", default="outputs/streaming_clients")
    args = ap.parse_args()

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    engines = {}
    for mode, streaming in (("dense", False), ("streaming", True)):
        ds = Synthetic(
            num_clients=args.clients, train_size=40 * args.clients,
            test_size=200, noise=0.3, cache=False, seed=0,
        )
        sim = Simulator(
            ds,
            aggregator="trimmedmean",
            aggregator_kws={"num_byzantine": 2},
            attack="signflipping",
            num_byzantine=2,
            log_path=os.path.join(args.out, mode),
            seed=42,
        )
        sim.run(
            "mlp",
            global_rounds=args.rounds,
            local_steps=2,
            client_lr=0.5,
            validate_interval=args.rounds,
            # 5 does not divide 24: the engine ceil-sizes and zero-pads
            # the final chunk (renormalizing the chunk count so no chunk
            # is pure padding)
            client_chunks=5,
            streaming=streaming,
        )
        gauges = memory_gauges(os.path.join(args.out, mode))
        engines[mode] = sim.engine
        mb = gauges["peak_update_bytes"] / 1e6
        print(
            f"[{mode:9s}] peak_update_bytes={gauges['peak_update_bytes']:.0f}"
            f" ({mb:.1f} MB), chunks={gauges['client_chunks']},"
            f" chunk_size={gauges['chunk_size']},"
            f" streaming={bool(gauges['streaming'])}"
        )

    dense_peak = engines["dense"].peak_update_bytes
    stream_peak = engines["streaming"].peak_update_bytes
    assert stream_peak < dense_peak, (dense_peak, stream_peak)
    print(
        f"update-memory ratio dense/streaming = {dense_peak / stream_peak:.1f}x"
        f" (chunk-independent of K: grows only with chunk_size * D)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
