"""Krum-collapse adjudication: fedavg-path IPM vs the reference's own Krum.

Round 3 committed a striking artifact (``results/fedavg_ipm``): with 20
clients, 8 of them running IPM, 30 fedavg rounds (10 local Adam steps,
persistent moments, MultiStepLR [15,25] gamma 0.5), the Krum-defended run
collapses to ~2% top-1 while the UNDEFENDED mean reaches ~88%. VERDICT r4
asked whether that is a genuine finding or a bug in our Krum.

This script settles it mechanically: both arms are re-run, and for EVERY
round the actual post-attack ``[K, D]`` update matrix is fed to

1. our production Krum (paper scoring, d^2),
2. our reference-parity Krum (``distance_power=4``), and
3. the reference's own ``Krum`` loaded verbatim from
   ``/root/reference/src/blades/aggregators/krum.py`` (torch),

recording each stack's selected client row. The committed result
(``results/fedavg_ipm/adjudication.json``): the reference-parity stack
(d^4) and the reference's own Krum select the SAME row in all 30 rounds
(agreement 1.0, max aggregate diff 0.0). The production d^2 default
agrees with that pair on 22/30 rounds; on the other 8 (rounds 7-11, 27,
29-30) the two scorings rank differently and d^2 selects one of the
bit-identical IPM rows while d^4 picks an honest one — so d^2 is
byzantine-captured for the first 11 consecutive rounds (14/30 overall),
the d^4/reference pair for the first 6. Either capture streak wrecks the
model, and the later honest selections are single-client Adam updates
that cannot recover it: the collapse is a property of Krum-vs-IPM, not
of this implementation. Mechanism: the 8 IPM rows are bit-identical
(every byzantine uploads ``-eps * mean(honest)``), so they give each
other pairwise distance 0 and win the sum-of-nearest-neighbors score
whenever the honest updates still carry strong, varied gradient signal —
every captured round applies ``-0.5 * mean(honest)``, a *reversed*
half-step of gradient ascent, which diverges. Mean, by contrast, still
moves in expectation by ``(12 - 8*0.5)/20 = +0.4x`` the honest
direction, so the undefended run trains through the attack.

Reference counterparts: ``attackers/ipmclient.py:4-16``,
``aggregators/krum.py:93-125``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

K, BYZ = 20, 8


def load_reference_krum():
    """The reference's own Krum, loaded verbatim (torch); None when the
    reference tree isn't mounted."""
    if not os.path.isdir("/root/reference/src"):
        return None
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from reference_loader import load_reference

    return load_reference().aggregators.krum


def run_arm(agg: str, out_dir: str, rounds: int, steps: int, seed: int,
            adjudicate):
    from blades_tpu import Simulator
    from blades_tpu.core import ClientOptSpec
    from blades_tpu.utils.logging import read_stats
    from examples.convergence_config1 import build_dataset

    ds, kind = build_dataset(os.path.join(REPO, "data"), num_clients=K,
                             seed=seed)
    log_path = os.path.join(out_dir, f"ipm_{agg}")
    sim = Simulator(
        dataset=ds,
        aggregator=agg,
        aggregator_kws={"num_byzantine": BYZ} if agg == "krum" else {},
        num_byzantine=BYZ,
        attack="ipm",
        log_path=log_path,
        seed=seed,
    )
    rows = []

    def on_round_end(rnd, state, m):
        if adjudicate and agg == "krum":
            rows.append(adjudicate(rnd, sim.engine.last_updates))

    sim.run(
        model="mlp",
        client_optimizer=ClientOptSpec(name="adam", persist=True),
        client_lr_scheduler={"milestones": [15, 25], "gamma": 0.5},
        global_rounds=rounds,
        local_steps=steps,
        client_lr=0.01,
        server_lr=1.0,
        validate_interval=rounds,
        on_round_end=on_round_end,
    )
    top1 = float(read_stats(log_path, type_filter="test")[-1]["top1"])
    return top1, rows, kind


def make_adjudicator(ref_krum_mod):
    """Per-round comparator: our Krum selections vs the reference's, on the
    identical update matrix."""
    import numpy as np
    import torch

    from blades_tpu.aggregators import get_aggregator

    ours_p2 = get_aggregator("krum", num_byzantine=BYZ)
    ours_p4 = get_aggregator("krum", num_byzantine=BYZ, distance_power=4)

    def adjudicate(rnd, updates):
        u = np.asarray(updates)
        sel_p2 = int(np.argmin(np.asarray(ours_p2.scores(u))))
        sel_p4 = int(np.argmin(np.asarray(ours_p4.scores(u))))
        row = {
            "round": rnd,
            "ours_selected": sel_p2,
            "ours_parity_selected": sel_p4,
            "selected_is_byzantine": sel_p2 < BYZ,
        }
        if ref_krum_mod is not None:
            tv = [torch.from_numpy(u[i].copy()) for i in range(len(u))]
            dists = ref_krum_mod._pairwise_euclidean_distances(tv)
            ref_sel = ref_krum_mod._multi_krum(dists, len(u), BYZ, 1)[0]
            ref_vec = ref_krum_mod.Krum(num_clients=len(u), num_byzantine=BYZ)(
                torch.from_numpy(u.copy())
            )
            ours_vec = np.asarray(ours_p4(u))
            row["reference_selected"] = int(ref_sel)
            row["agree_with_reference"] = bool(ref_sel == sel_p4)
            row["aggregate_max_abs_diff"] = float(
                np.max(np.abs(ours_vec - ref_vec.numpy()))
            )
        return row

    return adjudicate


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--out", default=os.path.join(REPO, "results", "fedavg_ipm"))
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    ref_krum = load_reference_krum()
    adjudicate = make_adjudicator(ref_krum)

    finals = {}
    adj_rows = []
    for agg in ("mean", "krum"):
        top1, rows, kind = run_arm(agg, args.out, args.rounds, args.steps,
                                   args.seed, adjudicate)
        finals[agg] = top1
        adj_rows.extend(rows)
        print(f"{agg}: final top1 = {top1:.4f}")

    agree = [r["agree_with_reference"] for r in adj_rows
             if "agree_with_reference" in r]
    diffs = [r["aggregate_max_abs_diff"] for r in adj_rows
             if "aggregate_max_abs_diff" in r]
    agreement = (sum(agree) / len(agree)) if agree else None
    max_diff = max(diffs) if diffs else None
    byz_picked = [r["selected_is_byzantine"] for r in adj_rows]
    # length of the opening byzantine-captured streak — the phase that
    # decides the run (once the model is wrecked, occasional honest
    # single-client Adam selections cannot recover it)
    streak = 0
    for b in byz_picked:
        if not b:
            break
        streak += 1
    if agree:
        headline = "krum collapse under IPM is genuine, not an implementation bug"
        cross_check = (
            "on every round's actual update matrix the reference's own Krum "
            f"selects the identical row (agreement {agreement}, max aggregate "
            f"diff {max_diff})"
        )
    else:
        # never claim the bug-vs-genuine verdict from a cross-check that did
        # not run; the committed adjudication (results/fedavg_ipm,
        # agreement 1.0) carries that evidence
        headline = (
            "krum selection dynamics are consistent with genuine "
            "IPM capture (verdict NOT re-adjudicated here)"
        )
        cross_check = (
            "reference tree not mounted — cross-check did not run; see the "
            "committed results/fedavg_ipm/adjudication.json for the "
            "reference-verified run"
        )
    verdict = {
        "rounds_checked": len(adj_rows),
        "reference_available": ref_krum is not None,
        "selection_agreement_with_reference": agreement,
        "fraction_rounds_krum_selected_byzantine":
            sum(byz_picked) / max(1, len(byz_picked)),
        "initial_byzantine_capture_streak": streak,
        "max_aggregate_abs_diff": max_diff,
        "conclusion": (
            f"{headline}: {cross_check}. "
            f"Krum is byzantine-captured for the first {streak} consecutive "
            f"rounds ({sum(byz_picked)}/{len(byz_picked)} overall): the "
            "identical IPM replicas have zero pairwise distance and win "
            "the nearest-neighbor score while the model still has signal; "
            "each captured round applies -eps*mean(honest). Later "
            "honest selections are single-client Adam updates (no "
            "averaging) and cannot recover the wrecked model."
        ),
        "per_round": adj_rows,
    }
    with open(os.path.join(args.out, "adjudication.json"), "w") as f:
        json.dump(verdict, f, indent=1)

    summary = {
        "config": f"fedavg path: {K} clients, {BYZ}xIPM, {args.rounds} "
                  f"rounds x {args.steps} local steps, client Adam "
                  "(persistent moments), MultiStepLR [15,25] g=0.5",
        "note": "BASELINE config-3 algorithm at MNIST scale; selection "
                "defense (krum, f=8) vs undefended mean; see "
                "adjudication.json for the per-round reference cross-check",
        "seed": args.seed,
        "final_top1": finals,
    }
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({**summary, "adjudication": {
        k: v for k, v in verdict.items() if k != "per_round"}}, indent=2))


if __name__ == "__main__":
    main()
