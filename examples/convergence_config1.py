"""BASELINE config 1 convergence evidence: ALIE vs mean / trimmedmean.

The reference's de-facto smoke test (``src/blades/examples/mini_example.py:19-50``):
MNIST-shaped MLP, 10 clients, 4 of them running the omniscient ALIE attack,
100 global rounds of 50 local SGD steps (batch 32, client_lr 0.1,
server_lr 1.0, SGD both sides). Three runs: a no-attack control, ``mean``
under attack, and ``trimmedmean`` under attack. ALIE is a *stealth* attack
(z_max ~ 0.43 at n=10, f=4 — the malicious rows sit inside the honest
spread by construction), so the expected signature is a measurable but
modest degradation of ``mean`` that the robust aggregator claws back, with
every run still converging; the catastrophic-attack separation lives in
``simulation_on_mnist.py`` (IPM, epsilon=100). Both together are the
accuracy-parity evidence on real attacked training curves.

Data: the real MNIST IDX files are used when present under ``--data-root``;
in zero-egress environments the class-prototype :class:`Synthetic` dataset
(same shape, 10 classes) stands in — the robustness claim being evidenced
(attacked convergence vs non-robust failure) is dataset-agnostic.

Outputs: ``results/config1/<agg>_stats`` (the run's stats log, one dict per
line), ``results/config1/summary.json``, and an accuracy-curve plot at
``docs/assets/config1_convergence.png``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def seed_stats(vals):
    """mean/min/max/n_seeds summary of per-seed finals (shared summary.json
    schema across the evidence scripts)."""
    vals = list(vals)
    return {
        "mean": sum(vals) / len(vals),
        "min": min(vals),
        "max": max(vals),
        "n_seeds": len(vals),
    }


def build_dataset(data_root: str, num_clients: int, seed: int):
    from blades_tpu.datasets import MNIST, Synthetic

    try:
        ds = MNIST(data_root=data_root, train_bs=32, num_clients=num_clients,
                   seed=seed)
        ds.get_dls()
        return ds, "mnist"
    except FileNotFoundError:
        # noise=0.3 puts the Bayes limit high (~90% for the MLP centrally)
        # while keeping the task non-trivial; at noise>=1.0 the prototypes
        # drown and no training run can demonstrate anything
        ds = Synthetic(
            num_classes=10,
            sample_shape=(28, 28, 1),
            train_size=10_000,
            test_size=1_000,
            noise=0.3,
            train_bs=32,
            num_clients=num_clients,
            seed=seed,
            cache=False,
        )
        return ds, "synthetic"


def run_one(aggregator: str, data_root: str, out_dir: str, rounds: int,
            seed: int = 1, attack: str = "alie", tag: str = None):
    """One config-1 run; returns the parsed ``test`` records."""
    from blades_tpu import Simulator

    tag = tag or aggregator
    log_path = os.path.join(out_dir, f"{tag}_logs")
    ds, ds_kind = build_dataset(data_root, num_clients=10, seed=seed)
    sim = Simulator(
        dataset=ds,
        aggregator=aggregator,
        num_byzantine=4 if attack else 0,
        attack=attack,
        attack_kws={"num_clients": 10, "num_byzantine": 4} if attack == "alie" else {},
        log_path=log_path,
        seed=seed,
    )
    sim.run(
        model="mlp",
        server_optimizer="SGD",
        client_optimizer="SGD",
        loss="crossentropy",
        global_rounds=rounds,
        local_steps=50,
        server_lr=1.0,
        client_lr=0.1,
        validate_interval=5,
    )
    from blades_tpu.utils.logging import read_stats

    shutil.copyfile(
        os.path.join(log_path, "stats"), os.path.join(out_dir, f"{tag}_stats")
    )
    return read_stats(log_path, type_filter="test"), ds_kind


def plot(curves: dict, path: str, bands: dict = None) -> None:
    """Accuracy-vs-round lines (seed-0 curve; min-max band across seeds
    when multi-seed data is provided)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    # categorical palette slots 1-3, fixed order
    colors = {
        "mean+alie": "#2a78d6",
        "trimmedmean+alie": "#eb6834",
        "mean (no attack)": "#1baf7a",
    }
    fig, ax = plt.subplots(figsize=(7, 4.2), dpi=150)
    for agg, tests in curves.items():
        xs = [t["Round"] for t in tests]
        ys = [100.0 * t["top1"] for t in tests]
        ax.plot(xs, ys, lw=2, color=colors.get(agg, "#666"), label=agg)
        if bands and agg in bands and len(bands[agg]) > 1:
            per_round = list(zip(*[[100.0 * t["top1"] for t in run]
                                   for run in bands[agg]]))
            lo = [min(v) for v in per_round]
            hi = [max(v) for v in per_round]
            ax.fill_between(xs, lo, hi, color=colors.get(agg, "#666"),
                            alpha=0.15, lw=0)
    # identity via the legend only: the three curves end within ~2 points
    # of each other, so direct end labels would collide
    ax.set_xlabel("Round")
    ax.set_ylabel("Test top-1 accuracy (%)")
    ax.set_title("Config 1: 10 clients, 4×ALIE (stealth) — with no-attack control")
    ax.set_ylim(0, 100)
    ax.grid(True, color="#e6e6e3", lw=0.6)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    ax.legend(frameon=False, loc="lower right")
    fig.tight_layout()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fig.savefig(path)
    plt.close(fig)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--data-root", default=os.path.join(REPO, "data"))
    p.add_argument("--out", default=os.path.join(REPO, "results", "config1"))
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument(
        "--plot",
        default=os.path.join(REPO, "docs", "assets", "config1_convergence.png"),
    )
    p.add_argument("--seeds", type=int, nargs="+", default=[1],
                   help="run every config once per seed; reports mean±range "
                        "so a 0.2-point defense-recovery claim is backed by "
                        "spread, not a single draw")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    runs = [
        ("mean (no attack)", "mean", None, "mean_noattack"),
        ("mean+alie", "mean", "alie", "mean_alie"),
        ("trimmedmean+alie", "trimmedmean", "alie", "trimmedmean_alie"),
    ]
    curves, bands, kind = {}, {}, None
    finals = {}
    for label, agg, attack, tag in runs:
        bands[label] = []
        finals[label] = {}
        for seed in args.seeds:
            stag = tag if seed == args.seeds[0] else f"{tag}_s{seed}"
            tests, kind = run_one(agg, args.data_root, args.out, args.rounds,
                                  seed=seed, attack=attack, tag=stag)
            bands[label].append(tests)
            finals[label][seed] = tests[-1]["top1"]
            print(f"{label} seed {seed}: final top1 = {tests[-1]['top1']:.4f}")
        curves[label] = bands[label][0]

    summary = {
        "config": "BASELINE config 1 (mini_example): MLP, 10 clients, "
                  "4xALIE, 100 rounds x 50 local steps",
        "dataset": kind,
        "seeds": args.seeds,
        "final_top1": {a: seed_stats(finals[a].values()) for a in finals},
        "final_top1_per_seed": finals,
        "final_loss": {a: curves[a][-1]["Loss"] for a in curves},
    }
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    plot(curves, args.plot, bands=bands)
    # compact print: the docs gallery keeps only the last few stdout lines,
    # so the headline numbers must fit (full detail lives in summary.json)
    print(json.dumps({k: v for k, v in summary.items()
                      if k != "final_top1_per_seed"}, indent=2))


if __name__ == "__main__":
    main()
