"""In-graph round metrics: per-round visibility that survives fused execution.

Round-block execution (``block_size=N``) and the streaming client axis
(``streaming=True``) fuse many rounds × chunks into single XLA launches —
host-side spans can no longer see inside a round, and the dense ``[K, D]``
update matrix the old forensics read may never exist. The in-graph
``MetricPack`` (``Simulator.run(round_metrics=True)``, or
``BLADES_ROUND_METRICS=1``) restores the per-round signal from INSIDE the
compiled program: update-norm quantiles + a fixed-log-bin histogram,
honest-vs-byzantine cosine-to-aggregate, participation counts, and
per-chunk slab extremes, one ``metrics`` telemetry record per round.

This demo runs the same seeded signflipping federation twice — once
per-round, once as a single 4-round block — and shows the per-round
``metrics`` records are identical across the two schedules (the tested
engine invariant), with the byzantine cosine pointing away from the
honest one. It closes with the run's measured program profile (the
``memory`` record: XLA cost-model flops/bytes + compiled buffer budget)
next to the analytical ``engine.peak_update_bytes`` gauge.

Usage: ``python examples/metrics_trace.py [--rounds 4] [--out DIR]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def _metrics_records(log_path: str):
    path = os.path.join(log_path, "telemetry.jsonl")
    out = {"metrics": [], "memory": [], "gauges": {}}
    for line in open(path):
        r = json.loads(line)
        if r["t"] == "metrics":
            out["metrics"].append(r)
        elif r["t"] == "memory":
            out["memory"].append(r)
        elif r["t"] == "round":
            out["gauges"] = r.get("gauges") or out["gauges"]
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--out", default=os.path.join(REPO, "results", "metrics_demo"))
    args = p.parse_args()

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic

    def run(log_dir, **kw):
        sim = Simulator(
            dataset=Synthetic(
                num_clients=8, train_size=640, test_size=160, noise=0.3,
                cache=False,
            ),
            num_byzantine=2,
            attack="signflipping",
            aggregator="median",
            log_path=log_dir,
            seed=0,
        )
        sim.run(
            "mlp", global_rounds=args.rounds, local_steps=1, client_lr=0.2,
            train_batch_size=8, validate_interval=args.rounds,
            round_metrics=True, **kw,
        )
        return _metrics_records(log_dir)

    seq = run(os.path.join(args.out, "per_round"))
    blk = run(os.path.join(args.out, "block"), block_size=args.rounds)

    print(f"{'round':>5} {'norm_median':>12} {'cos_honest':>11} "
          f"{'cos_byz':>8} {'participants':>13}")
    for m in seq["metrics"]:
        print(f"{m['round']:>5} {m['norm_median']:>12.4f} "
              f"{m['cos_honest']:>11.3f} {m['cos_byz']:>8.3f} "
              f"{m['participants']:>13}")

    same = all(
        a["norm_hist"] == b["norm_hist"]
        and a["participants"] == b["participants"]
        and abs(a["cos_honest"] - b["cos_honest"]) < 1e-5
        for a, b in zip(seq["metrics"], blk["metrics"])
    )
    print(f"\nper-round metrics identical under block_size={args.rounds}: "
          f"{same}")
    byz_away = sum(
        1 for m in seq["metrics"] if m["cos_byz"] < m["cos_honest"]
    )
    print(f"rounds where byzantine cosine < honest cosine: "
          f"{byz_away}/{len(seq['metrics'])} (signflipping points away)")

    if seq["memory"]:
        mem = seq["memory"][0]
        flops = mem.get("flops")
        print(f"\nmeasured program profile ({mem['program']}): "
              f"flops={flops:.3g}" if flops else "\nmeasured program profile:",
              f"temp_bytes={mem.get('temp_bytes')}")
    peak = seq["gauges"].get("engine.peak_update_bytes")
    if peak:
        print(f"analytical peak_update_bytes gauge: {peak} "
              f"({peak / 1e6:.1f} MB)")


if __name__ == "__main__":
    main()
