"""Telemetry trace: span tree, compile accounting, and defense forensics.

Every :meth:`Simulator.run` writes a JSONL telemetry trace next to its
``stats`` log (``<log_path>/telemetry.jsonl``) unless ``BLADES_TELEMETRY=0``:
a per-round span tree (sample / dispatch / device sync / eval), XLA
compile + persistent-cache counters, and — with ``collect_diagnostics=True``
— *what the defense decided* each round (here: which coordinates
trimmed-mean discarded, and how much of the trimmed mass came from the
actual byzantine clients running ALIE).

The reference has no counterpart for any of this: it logs only whole-round
wall time and loss/accuracy (``src/blades/simulator.py:453-455``).

This demo runs a small MLP federation for a few rounds, then summarizes the
trace with ``scripts/trace_summary.py`` — the same per-stage cost table you
would read off a real TPU run.

Usage: ``python examples/telemetry_trace.py [--rounds 2] [--out DIR]``
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--out", default=os.path.join(REPO, "results", "telemetry_demo"))
    args = p.parse_args()

    from blades_tpu import Simulator
    from blades_tpu.datasets import Synthetic
    from trace_summary import format_table, load_records, summarize

    log_path = os.path.join(args.out, "logs")
    sim = Simulator(
        dataset=Synthetic(
            num_clients=8, train_size=800, test_size=160, noise=0.3, cache=False
        ),
        num_byzantine=2,
        attack="alie",
        aggregator="trimmedmean",
        aggregator_kws={"num_byzantine": 2},
        log_path=log_path,
        seed=0,
    )
    times = sim.run(
        "mlp",
        global_rounds=args.rounds,
        local_steps=2,
        client_lr=0.2,
        server_lr=1.0,
        train_batch_size=8,
        validate_interval=args.rounds,
        collect_diagnostics=True,
    )

    trace = os.path.join(log_path, "telemetry.jsonl")
    if not os.path.exists(trace):
        # the run itself is unaffected by the kill switch; there is just
        # nothing to summarize
        print("BLADES_TELEMETRY=0: no trace written "
              f"(run completed in {sum(times):.3f}s)")
        return
    summary = summarize(load_records(trace))
    print(format_table(summary))
    round_total = summary["spans"]["round"]["total_s"]
    print(f"\nengine round wall total: {sum(times):.3f}s "
          f"(trace round-span total: {round_total:.3f}s)")
    # the forensic signal: how much of what the defense trimmed was byzantine
    byz_trim = summary["defense"].get("mean_byz_trim_frac")
    if byz_trim is not None:
        print(f"byz share of trimmed coordinate-slots: {byz_trim:.2f} "
              f"(2 of 8 clients byzantine -> blind trimming would give 0.25)")


if __name__ == "__main__":
    main()
