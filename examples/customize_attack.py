"""
Customization of attack strategy
================================

Reference flow: subclass ``ByzantineClient`` and override lifecycle methods
(``src/blades/examples/customize_attack.py``). Here the hooks are *pure
functions* that run inside the compiled round program — subclass
:class:`blades_tpu.attackers.Attack` for the transform and attach it to a
:class:`blades_tpu.client.ByzantineClient`:

- ``on_grads``    — corrupt per-step gradients (replaces overriding
  ``local_training`` for sign-flip-style attacks).
- ``on_batch``    — modify each training batch (``on_train_batch_begin``).
- ``on_updates``  — full omniscient knowledge: rewrite rows of the global
  ``[K, D]`` update matrix (``omniscient_callback``).
"""

import sys

from blades_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

import jax.numpy as jnp

from blades_tpu.attackers.base import Attack, honest_stats
from blades_tpu.client import ByzantineClient
from blades_tpu.datasets import MNIST, Synthetic
from blades_tpu.simulator import Simulator


class MaliciousAttack(Attack):
    """Sign-flips gradients, flips labels, and uploads -100x the honest
    mean — the same triple attack as the reference example."""

    trains_dishonestly = True

    def __init__(self, num_classes=10):
        self.num_classes = num_classes

    def on_batch(self, x, y, is_byz, *, num_classes, key):
        return x, jnp.where(is_byz, self.num_classes - 1 - y, y)

    def on_grads(self, grads, is_byz):
        import jax

        sign = jnp.where(is_byz, -1.0, 1.0)
        return jax.tree_util.tree_map(lambda g: g * sign.astype(g.dtype), grads)

    def on_updates(self, updates, byz_mask, key, state=()):
        mu, _, _ = honest_stats(updates, byz_mask)
        return jnp.where(byz_mask[:, None], -100.0 * mu[None, :], updates), state


class MaliciousClient(ByzantineClient):
    def make_attack(self):
        return MaliciousAttack()


if "--synthetic" in sys.argv:
    dataset = Synthetic(num_clients=10, train_bs=32, train_size=4000)
else:
    dataset = MNIST(data_root="./data", train_bs=32, num_clients=10)

simulator = Simulator(
    dataset=dataset,
    aggregator="clippedclustering",  # defense: robust aggregation
    seed=1,
)
# replace the first 5 clients with the custom attacker
simulator.register_attackers([MaliciousClient() for _ in range(5)])

simulator.run(
    model="mlp",
    server_optimizer="SGD",
    client_optimizer="SGD",
    loss="crossentropy",
    global_rounds=50,
    local_steps=50,
    server_lr=1.0,
    client_lr=0.1,
)
