"""Defense certification + runtime audit: search, certify, fall back.

The paper's actual claim is not that runs survive — it is that the
*defenses* are Byzantine-robust. This demo exercises the audit subsystem
(``blades_tpu/audit``, ``docs/robustness.md``) that measures and reacts to
defense breakdown:

1. **offline certification** — the adaptive attack search (IPM/ALIE/
   sign-flip sweeps + min-max/min-sum bisection, NDSS'21 style) runs over
   a few defenses at their nominal f: the robust ones stay within
   ``c = 3`` honest spreads of the honest mean; plain ``mean`` is dragged
   orders of magnitude away (breakdown point 0);
2. **runtime audit + fallback** — a federation aggregating with ``mean``
   under a strong IPM attack, with the runtime monitor's median-ball /
   envelope certificates traced into the jitted round: every breached
   round swaps in the ``median`` fallback in-graph, the model converges
   anyway, and per-round ``audit`` telemetry records the forensics;
3. the breach->fallback run is **bit-reproducible**: rerunning the same
   seed reproduces the final parameters exactly.

The committed full matrix lives at
``results/certification/cert_matrix.json`` (``python scripts/certify.py``).

Usage: ``python examples/defense_audit.py [--rounds 4] [--out DIR]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from blades_tpu.utils.platform import apply_env_platform  # noqa: E402

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--out", default=os.path.join(REPO, "results", "audit_demo"))
    args = p.parse_args()

    import jax
    import numpy as np

    from blades_tpu import Simulator
    from blades_tpu.aggregators import get_aggregator
    from blades_tpu.audit import (
        QUICK_GRIDS,
        battery_ctx,
        battery_kwargs,
        nominal_f,
        search_cell,
        synthetic_honest,
    )
    from blades_tpu.datasets import Synthetic
    from blades_tpu.ops.pytree import ravel

    # -- 1. offline certification: worst-case deviation per defense ---------
    K, D = 8, 32
    trials = synthetic_honest(jax.random.PRNGKey(0), 2, K, D)
    ctx = battery_ctx(None, K, D)
    print(f"adaptive attack search, K={K}, worst deviation / honest spread "
          f"(certified iff <= 3):")
    for name in ("mean", "median", "krum", "centeredclipping"):
        f = max(1, nominal_f(name, K))
        agg = get_aggregator(name, **battery_kwargs(name, K, f))
        cell = search_cell(agg, trials, f, ctx=ctx, grids=QUICK_GRIDS)
        verdict = "CERTIFIED" if cell["worst_ratio"] <= 3.0 else "BREAKS"
        print(f"  {name:18s} f={f}  worst_ratio={cell['worst_ratio']:8.2f}  "
              f"{verdict}")

    # -- 2. runtime audit: mean under IPM, certified fallback to median -----
    def build(sub, seed=7):
        return Simulator(
            dataset=Synthetic(num_clients=K, train_size=800, test_size=160,
                              noise=0.3, cache=False),
            aggregator="mean",
            attack="ipm", attack_kws={"epsilon": 50.0}, num_byzantine=2,
            log_path=os.path.join(args.out, sub), seed=seed,
        )

    run_kw = dict(global_rounds=args.rounds, local_steps=2, client_lr=0.2,
                  server_lr=1.0, train_batch_size=8,
                  validate_interval=args.rounds,
                  audit_monitor=dict(fallback_aggregator="median"))

    sim = build("audited")
    sim.run("mlp", **run_kw)
    ev = sim.evaluate(args.rounds, 64)
    assert np.isfinite(ev["Loss"]), "audited run went non-finite!"

    trace = os.path.join(args.out, "audited", "telemetry.jsonl")
    audits = []
    if os.path.exists(trace):  # BLADES_TELEMETRY=0 disables the trace
        with open(trace) as f:
            audits = [r for r in map(json.loads, f) if r.get("t") == "audit"]
    print(f"\nmean + IPM(eps=50), 2/{K} byzantine, fallback=median:")
    for r in audits:
        print(f"  round {r['round']}: breach={r['breach']} "
              f"fallback_used={r['fallback_used']} "
              f"dev_honest(raw)={r['dev_honest_raw']:.3f} "
              f"dev_honest(applied)={r['dev_honest']:.3f} "
              f"(honest spread {r['max_honest_dev']:.3f})")
    print(f"final eval: loss={ev['Loss']:.4f} top1={ev['top1']:.3f}")
    if audits:
        assert all(r["fallback_used"] == r["breach"] for r in audits)
        assert any(r["breach"] for r in audits), "IPM never breached?"

    # -- 3. breach->fallback rounds are bit-reproducible ---------------------
    again = build("audited_rerun")
    again.run("mlp", **run_kw)
    a = np.asarray(ravel(sim.server.state.params))
    b = np.asarray(ravel(again.server.state.params))
    exact = bool(np.array_equal(a, b))
    print(f"breach->fallback run bit-reproducible under the same seed: {exact}")
    assert exact


if __name__ == "__main__":
    main()
