"""
A mini example
==============

The de-facto smoke test (reference: ``src/blades/examples/mini_example.py``):
federated MNIST, 10 clients of which 4 run the ALIE attack, mean aggregation,
MLP global model. No ``ray.init`` needed — parallelism comes from the device
mesh automatically.

Run with real MNIST under ``./data`` (IDX files or mnist.npz), or pass
``--synthetic`` to use the offline stand-in dataset.
"""

import os
import sys

from blades_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

from blades_tpu.datasets import MNIST, Synthetic
from blades_tpu.simulator import Simulator

if "--synthetic" in sys.argv:
    dataset = Synthetic(num_clients=10, train_bs=32, train_size=4000)
else:
    dataset = MNIST(data_root="./data", train_bs=32, num_clients=10)

conf_params = {
    "dataset": dataset,
    "aggregator": "mean",  # aggregation
    "num_byzantine": 4,  # number of Byzantine clients
    "attack": "alie",  # attack strategy
    "attack_kws": {"num_clients": 10, "num_byzantine": 4},
    "seed": 1,  # reproducibility
}

simulator = Simulator(**conf_params)

run_params = {
    "model": "mlp",  # global model (reference: MLP())
    "server_optimizer": "SGD",
    "client_optimizer": "SGD",
    "loss": "crossentropy",
    # env knobs let the docs gallery execute a reduced run
    "global_rounds": int(os.environ.get("MINI_ROUNDS", 100)),
    "local_steps": int(os.environ.get("MINI_STEPS", 50)),
    "server_lr": 1.0,
    "client_lr": 0.1,
}
simulator.run(**run_params)
