"""Multi-host / multi-slice federated training.

The reference scales out by deploying a Ray cluster and shipping client
objects to actor processes (``README.rst:146-149``, ``simulator.py:90-98``).
Here every host of a TPU pod (or multi-slice job) runs THIS SAME script;
``jax.distributed`` fuses them into one SPMD runtime and the compiler
schedules all cross-host traffic (ICI inside a slice, DCN across slices).

Launch (one command per host, e.g. via gcloud or your cluster runner)::

    python examples/multihost_pod.py

Works unchanged on a single host — the distributed init is a no-op there.
"""

import os

from blades_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

import jax
import numpy as np

from blades_tpu.aggregators import get_aggregator
from blades_tpu.core import ClientOptSpec, RoundEngine, ServerOptSpec
from blades_tpu.datasets.augment import make_normalizer
from blades_tpu.models import cct_2_3x2_32
from blades_tpu.models.common import build_fns
from blades_tpu.parallel import distributed as dist
from blades_tpu.parallel.mesh import make_plan

# env knobs: the docs gallery and smoke runs execute a reduced config
K = int(os.environ.get("POD_CLIENTS", 1024))           # client population
LOCAL_STEPS = int(os.environ.get("POD_STEPS", 2))
BATCH = int(os.environ.get("POD_BATCH", 32))
ROUNDS = int(os.environ.get("POD_ROUNDS", 10))
SAMPLES_PER_CLIENT = int(os.environ.get("POD_SAMPLES", 64))


def main():
    dist.initialize()  # no-op single-host; joins the pod otherwise
    mesh = dist.make_global_mesh()
    plan = make_plan(mesh)
    if dist.is_coordinator():
        print(f"mesh: {mesh}, {jax.process_count()} hosts")

    # Each host materializes ONLY its own client rows.
    lo, hi = dist.host_client_slice(K, mesh)
    rng = np.random.RandomState(0)
    local_x = rng.randint(
        0, 256, (hi - lo, SAMPLES_PER_CLIENT, 32, 32, 3), dtype=np.uint8
    ).astype(np.float32)
    local_y = rng.randint(0, 10, (hi - lo, SAMPLES_PER_CLIENT)).astype(np.int32)
    normalize = make_normalizer((0.49, 0.48, 0.44), (0.25, 0.24, 0.26))

    spec = build_fns(cct_2_3x2_32(num_classes=10), sample_shape=(32, 32, 3))
    params = spec.init(jax.random.PRNGKey(0))
    engine = RoundEngine(
        spec.train_loss_fn,
        spec.eval_logits_fn,
        params,
        num_clients=K,
        aggregator=get_aggregator("trimmedmean"),
        client_opt=ClientOptSpec(),
        server_opt=ServerOptSpec(),
        plan=plan,
        client_chunks=4,
        remat=True,
    )
    state = engine.init(params)

    key = jax.random.PRNGKey(7)
    for r in range(ROUNDS):
        # sample this host's batches, assemble the global [K, S, B, ...] array
        k = jax.random.fold_in(key, r)
        idx = np.asarray(
            jax.random.randint(
                k, (hi - lo, LOCAL_STEPS * BATCH), 0, SAMPLES_PER_CLIENT
            )
        )
        bx = np.take_along_axis(local_x, idx[..., None, None, None], axis=1)
        by = np.take_along_axis(local_y, idx, axis=1)
        cx = dist.make_global_client_array(
            np.asarray(
                normalize(bx).reshape(hi - lo, LOCAL_STEPS, BATCH, 32, 32, 3)
            ),
            K,
            plan,
        )
        cy = dist.make_global_client_array(
            by.reshape(hi - lo, LOCAL_STEPS, BATCH), K, plan
        )
        state, m = engine.run_round(state, cx, cy, 0.1, 1.0, key)
        if dist.is_coordinator():
            print(f"round {r + 1}: loss={float(m.train_loss):.4f}")

    dist.sync_global_devices("done")


if __name__ == "__main__":
    main()
