"""
Comparing built-in aggregation schemes
======================================

Reference: ``src/blades/examples/plot_comparing_aggregation_schemes.py`` —
60 benign 2-D Gaussian samples + 40 outliers pushed through every aggregator;
robust ones must land inside the benign cluster. This doubles as the
statistical sanity check the test suite formalizes (tests/test_aggregators.py).
"""

import os

import numpy as np

from blades_tpu.utils.platform import apply_env_platform

apply_env_platform()  # honor JAX_PLATFORMS=cpu launchers (docs/build.py)

import jax.numpy as jnp

from blades_tpu.aggregators import AGGREGATORS, get_aggregator

np.random.seed(1)
benign = np.random.normal(0.0, 1.0, (60, 2))
outlier = np.random.normal(7.0, 1.0, (40, 2))
data = jnp.asarray(np.concatenate([benign, outlier]).astype(np.float32))

results = {}
for name in sorted(AGGREGATORS):
    # fltrust needs a designated trusted row; byzantinesgd needs the
    # params_flat/round context the engine threads through — both are
    # exercised in tests/test_aggregators.py instead
    if name in ("fltrust", "byzantinesgd"):
        continue
    agg = get_aggregator(name)
    results[name] = np.asarray(agg(data))
    dist = np.linalg.norm(results[name] - benign.mean(0))
    tag = "ROBUST" if dist < 1.0 else "pulled"
    print(f"{name:18s} -> {np.round(results[name], 3)}  (dist to benign mean: {dist:5.2f}) {tag}")

try:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    plt.scatter(benign[:, 0], benign[:, 1], s=8, alpha=0.4, label="benign")
    plt.scatter(outlier[:, 0], outlier[:, 1], s=8, alpha=0.4, label="outlier")
    for name, p in results.items():
        plt.scatter(*p, marker="x", s=60)
        plt.annotate(name, p, fontsize=7)
    plt.legend()
    out = os.environ.get("AGG_PLOT_OUT", "aggregation_schemes.png")
    plt.savefig(out, dpi=120)
    print(f"wrote {out}")
except Exception as e:  # matplotlib optional
    print(f"(plot skipped: {e})")
