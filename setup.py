"""Packaging (reference: ``src/setup.py`` — pip package ``blades`` v0.0.14).

Dependencies are the TPU-native substrate: jax/flax/optax replace the
reference's torch+ray+sklearn stack (``src/setup.py:5-16``).
"""

from setuptools import find_packages, setup

setup(
    name="blades-tpu",
    version="0.1.0",
    description=(
        "TPU-native (JAX/XLA) simulator for Byzantine attacks and robust "
        "aggregation defenses in federated learning"
    ),
    long_description=open("README.md").read(),
    long_description_content_type="text/markdown",
    packages=find_packages(exclude=("tests", "examples", "scripts")),
    python_requires=">=3.10",
    install_requires=[
        "jax>=0.4.30",
        "flax>=0.8",
        "optax>=0.2",
        "numpy",
        "scipy",
    ],
    extras_require={
        "test": ["pytest", "chex"],
        "checkpoint": ["orbax-checkpoint"],
    },
    license="Apache-2.0",
)
